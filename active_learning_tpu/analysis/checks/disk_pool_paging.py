"""disk-pool-paging: paging-path functions never materialize the store.

The disk tier's one scaling claim — "a pool bigger than host RAM pages
through a bounded cache" (DESIGN.md §16) — dies the moment any function
on the paging path reads the whole extent into host memory: one
``np.asarray(mm)`` and the demand-paged backend quietly becomes the
in-memory backend with extra steps, OOMing exactly at the scale it
exists for.  The spy counters in tests/test_disk_pool.py prove
boundedness dynamically for the paths a test drives; this checker
proves it statically for every path.

The registry is closed: a module declaring ``_PAGED_READERS`` (a tuple
of function names — data/diskpool.py) nominates the ONLY functions
allowed to touch the disk extent, and every listed name must resolve to
a module-level function or a method in some class body — a
registered-but-missing reader means the registry drifted from the code.

Inside each registered function, three materialization shapes are
forbidden on any STORE-NAMED value (terminal name ``mm``/``*_mm``, or
carrying the ``store`` word — the memmap and its aliases):

  1. whole-array constructors: ``np.asarray(mm)`` / ``np.array(mm)`` /
     ``np.ascontiguousarray(mm)`` — one call, whole pool in RAM;
  2. the full slice ``mm[:]`` (no bounds) — same copy, subscript
     spelling;
  3. ``mm.copy()`` / ``mm.tolist()`` — method spellings of the same.

Like the sibling checkers the walk is LEXICAL: bounded block slices
(``mm[lo:hi]``) pass because they carry bounds, and aliases are
recognized by name shape, not dataflow — name the memmap like a memmap.

Suppression: ``# al-lint: paging-ok <reason>`` on the flagged line.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from ..engine import Checker, Context
from ..findings import Finding

_MATERIALIZERS = ("asarray", "array", "ascontiguousarray")
_COPY_METHODS = ("copy", "tolist")
_STORE_NAME = re.compile(r"((^|_)mm$|store)", re.IGNORECASE)


def _paged_registry(tree: ast.Module) -> Optional[List[str]]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "_PAGED_READERS"
                for t in node.targets):
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                return []
            return [elt.value for elt in node.value.elts
                    if isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)]
    return None


def _terminal_name(node: ast.AST) -> str:
    """The rightmost name of a Name/Attribute chain (``self._mm`` ->
    ``_mm``), or "" for anything else."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_store_named(node: ast.AST) -> bool:
    return bool(_STORE_NAME.search(_terminal_name(node)))


def _registered_functions(tree: ast.Module, names: List[str]):
    """Every def matching a registered name — module level AND inside
    class bodies (the paging path is mostly methods)."""
    found = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in names:
            found.setdefault(node.name, []).append(node)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name in names:
                    found.setdefault(sub.name, []).append(sub)
    return found


class DiskPoolPagingChecker(Checker):
    id = "disk-pool-paging"
    title = ("paging-path functions (the _PAGED_READERS registry) never "
             "materialize the whole pool store")
    suppress_token = "paging-ok"

    def check(self, ctx: Context) -> List[Finding]:
        problems: List[Finding] = []
        for path in ctx.files:
            tree, err = ctx.tree(path)
            if err is not None:
                continue  # parse failures are the legacy checks' finding
            registry = _paged_registry(tree)
            if registry is None:
                continue
            rel = ctx.rel(path)
            fns = _registered_functions(tree, registry)
            for name in registry:
                if name not in fns:
                    problems.append(Finding(
                        check=self.id, path=rel, line=0,
                        message=(f"_PAGED_READERS names {name!r} but no "
                                 "function or method defines it — the "
                                 "closed registry drifted from the code"),
                        hint="define the reader or fix the registry"))
                    continue
                for fn in fns[name]:
                    self._check_bounded(fn, rel, problems)
        return problems

    def _check_bounded(self, fn, rel, problems):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = node.func
                if (isinstance(callee, ast.Attribute)
                        and callee.attr in _MATERIALIZERS
                        and node.args
                        and _is_store_named(node.args[0])):
                    problems.append(self._finding(
                        fn, rel, node.lineno,
                        f"np.{callee.attr}("
                        f"{_terminal_name(node.args[0])}) copies the "
                        "WHOLE store into host memory"))
                elif (isinstance(callee, ast.Attribute)
                        and callee.attr in _COPY_METHODS
                        and _is_store_named(callee.value)):
                    problems.append(self._finding(
                        fn, rel, node.lineno,
                        f"{_terminal_name(callee.value)}."
                        f"{callee.attr}() materializes the whole "
                        "store"))
            elif (isinstance(node, ast.Subscript)
                    and _is_store_named(node.value)
                    and isinstance(node.slice, ast.Slice)
                    and node.slice.lower is None
                    and node.slice.upper is None):
                problems.append(self._finding(
                    fn, rel, node.lineno,
                    f"{_terminal_name(node.value)}[:] slices the whole "
                    "store — a full copy in subscript spelling"))

    def _finding(self, fn, rel, line, what):
        return Finding(
            check=self.id, path=rel, line=line,
            message=(f"'{fn.name}' is on the paging path "
                     f"(_PAGED_READERS) but {what} — the demand-paged "
                     "backend must never hold more than one block "
                     "beyond the cache budget"),
            hint="read bounded, bucket-aligned block slices instead, or "
                 "annotate '# al-lint: paging-ok <reason>'")
