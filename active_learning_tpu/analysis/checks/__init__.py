"""The checker registry: 10 ported legacy checks + 8 deep checkers.

Ordered — the CLI lists and runs them in this order, and the per-check
fixture test parametrizes over it.  Adding a check = appending here
(see engine.py's module docstring for the recipe).
"""

from __future__ import annotations

from .legacy import LEGACY_CHECKERS
from .lock_discipline import LockDisciplineChecker
from .donation import DonationSafetyChecker
from .recompile import RecompileHazardChecker
from .collective_axis import CollectiveAxisChecker
from .diagnostics_inert import DiagnosticsInertChecker
from .wal_before_ack import WalBeforeAckChecker
from .disk_pool_paging import DiskPoolPagingChecker
from .fleet_host_pure import FleetHostPureChecker

DEEP_CHECKERS = (
    LockDisciplineChecker(),
    DonationSafetyChecker(),
    RecompileHazardChecker(),
    CollectiveAxisChecker(),
    DiagnosticsInertChecker(),
    WalBeforeAckChecker(),
    DiskPoolPagingChecker(),
    FleetHostPureChecker(),
)

CHECKERS = tuple(LEGACY_CHECKERS) + DEEP_CHECKERS

CHECK_IDS = tuple(c.id for c in CHECKERS)
