"""fleet-host-pure: the fleet layer never touches jax, and its journal
writes cannot tear.

The fleet controller (active_learning_tpu/fleet/, DESIGN.md §17) runs
on a CPU-only head node scheduling experiments onto workers whose
accelerators it can never initialize — one ``import jax`` anywhere in
the package and the controller dies at import time on exactly the
machine it exists for.  And its single source of truth, the fleet
journal, is only crash-safe because every write goes through ONE
atomic tmp+rename helper; a second ``json.dump`` path added in a hurry
would reintroduce the torn-write corruption the journal design exists
to rule out.  Both properties are structural, so this checker proves
them statically:

  1. **Host purity.**  A module declaring ``_FLEET_MODULE = True``
     (every module in the fleet package — the closed registry) may not
     import jax in any form or reference the ``jax`` name.  stdlib
     only: the controller consumes heartbeats, journals, and scrape
     files — never arrays.

  2. **Atomic journal writes.**  Inside a marked module, every
     ``json.dump`` call must sit lexically inside a function named
     ``write_atomic_json``, and every such function must contain the
     ``os.replace`` that makes it atomic.  (``json.dumps`` to a string
     is fine — only the direct-to-file spelling can tear.)

  3. **Coverage.**  Every ``.py`` under ``active_learning_tpu/fleet/``
     must declare the marker — a new fleet module cannot opt out of
     rules 1–2 by forgetting the registry line.

Like its siblings the walk is LEXICAL: ``from json import dump`` would
evade rule 2's name match — don't do that (review owns renames; the
checker owns the honest spelling).

Suppression: ``# al-lint: fleet-ok <reason>`` on the flagged line.
"""

from __future__ import annotations

import ast
import os
from typing import List

from ..engine import Checker, Context
from ..findings import Finding


def _declares_fleet(tree: ast.Module) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            names = {t.id for t in node.targets
                     if isinstance(t, ast.Name)}
            if "_FLEET_MODULE" in names:
                return (isinstance(node.value, ast.Constant)
                        and node.value.value is True)
    return False


class FleetHostPureChecker(Checker):
    id = "fleet-host-pure"
    title = ("the fleet layer (_FLEET_MODULE registry) never imports jax "
             "and journals only through the atomic tmp+rename helper")
    suppress_token = "fleet-ok"

    def check(self, ctx: Context) -> List[Finding]:
        problems: List[Finding] = []
        for path in ctx.files:
            tree, err = ctx.tree(path)
            if err is not None:
                continue  # parse failures are the legacy checks' finding
            rel = ctx.rel(path)
            in_fleet = ("active_learning_tpu/fleet/"
                        in rel.replace(os.sep, "/"))
            marked = _declares_fleet(tree)
            if in_fleet and not marked:
                problems.append(Finding(
                    check=self.id, path=rel, line=1,
                    message=("module under active_learning_tpu/fleet/ "
                             "does not declare '_FLEET_MODULE = True' — "
                             "every fleet module joins the closed "
                             "registry so none can opt out of the "
                             "host-purity and atomic-journal rules"),
                    hint="add '_FLEET_MODULE = True' at module level"))
            if marked:
                self._check_host_pure(tree, rel, problems)
                self._check_atomic_journal(tree, rel, problems)
        return problems

    # -- rule 1: host purity ----------------------------------------------

    def _check_host_pure(self, tree, rel, problems):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "jax":
                        problems.append(self._pure_finding(
                            rel, node.lineno,
                            "imports jax — the fleet layer runs on a "
                            "CPU-only head node that can never "
                            "initialize a worker's accelerator"))
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "jax":
                    problems.append(self._pure_finding(
                        rel, node.lineno,
                        "imports from jax — the fleet layer must stay "
                        "stdlib-only"))
            elif isinstance(node, ast.Name) and node.id == "jax":
                problems.append(self._pure_finding(
                    rel, node.lineno,
                    "references the jax name inside a fleet module"))

    def _pure_finding(self, rel, line, message):
        return Finding(
            check=self.id, path=rel, line=line,
            message=f"host-purity violation: {message}",
            hint="keep device work in the launched run children — the "
                 "controller consumes heartbeats/journals/scrape files, "
                 "or annotate '# al-lint: fleet-ok <reason>'")

    # -- rule 2: atomic journal writes ------------------------------------

    def _check_atomic_journal(self, tree, rel, problems):
        def visit(node, inside_helper: bool):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == "write_atomic_json":
                    inside_helper = True
                    if not any(
                            isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Attribute)
                            and n.func.attr == "replace"
                            and isinstance(n.func.value, ast.Name)
                            and n.func.value.id == "os"
                            for n in ast.walk(node)):
                        problems.append(Finding(
                            check=self.id, path=rel, line=node.lineno,
                            message=("'write_atomic_json' contains no "
                                     "os.replace — the helper lost the "
                                     "tmp+rename that makes journal "
                                     "writes atomic"),
                            hint="write to a tmp path, then os.replace "
                                 "it over the journal"))
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "dump"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "json"
                    and not inside_helper):
                problems.append(Finding(
                    check=self.id, path=rel, line=node.lineno,
                    message=("json.dump outside 'write_atomic_json' — a "
                             "fleet-package file write that can tear; "
                             "the journal's crash-safety claim holds "
                             "only through the one atomic helper"),
                    hint="route the write through "
                         "journal.write_atomic_json, or annotate "
                         "'# al-lint: fleet-ok <reason>'"))
            for child in ast.iter_child_nodes(node):
                visit(child, inside_helper)

        visit(tree, False)
