"""Whole-package static analysis (DESIGN.md §12).

One engine, one parse per file, 18 checks: the 10 invariants the old
``scripts/trace_lint.py`` monolith enforced (ported verbatim — same
verdicts, same messages) plus eight deep checkers targeting the bug
classes three consecutive PRs of code review kept re-finding:

  lock-discipline    _GUARDED_BY fields only touched under their lock
  donation-safety    no use-after-donate of donated jit buffers
  recompile-hazard   jit confined to step-builders, no fresh statics
  collective-axis    collectives name registered mesh axes; owner_rows
                     is the one masked-psum spelling
  diagnostics-inert  the experiment-truth layer is host-pure and its
                     hot-path hooks are flag-gated (DESIGN.md §13)
  wal-before-ack     streaming ingest handlers append to the fsync'd
                     WAL before constructing any ack, and stay
                     host-pure (DESIGN.md §14)
  disk-pool-paging   paging-path functions (the _PAGED_READERS
                     registry) never materialize the whole pool store
                     on one host (DESIGN.md §16)

Entry points: ``scripts/al_lint.py`` (CLI: --check/--list/--json),
``scripts/trace_lint.py`` (the legacy compatibility shim), and
``run_package_analysis()`` below for programmatic use (the tier-1
fail-fast test).  Stdlib only — no jax anywhere in this package.
"""

from __future__ import annotations

from .engine import AstCache, Checker, Context, Engine, default_files
from .findings import Finding, Report


def run_package_analysis(check_ids=None, files=None) -> Report:
    """Run the full registry (or a subset) over the package tree."""
    from .checks import CHECKERS

    return Engine(files=files).run(CHECKERS, check_ids=check_ids)


__all__ = ["AstCache", "Checker", "Context", "Engine", "Finding",
           "Report", "default_files", "run_package_analysis"]
