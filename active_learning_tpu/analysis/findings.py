"""The findings model: what a checker reports and how a report renders.

One ``Finding`` is one violation at one place: a check id, a
repo-relative path, a line (0 = file/registry-level), a human message,
and an optional fix hint.  The ported trace_lint checks render their
findings byte-for-byte as the legacy strings (``path:line: message`` /
``path: message``), which is what lets scripts/trace_lint.py stay a thin
shim with identical verdicts.

Suppressions: the four deep checkers (lock-discipline, donation-safety,
recompile-hazard, collective-axis) honor a source-line annotation

    # al-lint: <token> <reason>

where ``token`` is the checker's ``suppress_token`` (e.g. ``donated-ok``).
A suppression REQUIRES a non-empty reason — one without a reason is
itself a finding, and suppressed findings are counted and carried in the
``--json`` report rather than vanishing (the operator always sees how
much of the tree is annotated away).  The legacy checks deliberately
accept no suppressions: their verdicts must stay identical to the
monolith they replace.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


@dataclass
class Finding:
    check: str               # check id (see checks/__init__.CHECKERS)
    path: str                # repo-relative path
    line: int                # 1-based; 0 = file/registry-level finding
    message: str             # human-readable defect statement
    hint: str = ""           # how to fix (empty for legacy-ported checks)
    suppressed: bool = False
    suppress_reason: str = ""

    def render(self) -> str:
        """The legacy trace_lint string shape: ``path:line: message`` (or
        ``path: message`` for file-level findings).  The hint rides after
        the message so the shim's strings stay supersets of the legacy
        text, never rewrites of it."""
        loc = f"{self.path}:{self.line}" if self.line else self.path
        text = f"{loc}: {self.message}"
        if self.hint:
            text += f"  [fix: {self.hint}]"
        return text

    def to_json(self) -> dict:
        return {
            "check": self.check,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "hint": self.hint,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
        }


# ``# al-lint: <token> <reason...>`` — reason is everything after the
# token (may be empty, which is itself a finding).
_SUPPRESS_RE = re.compile(r"#\s*al-lint:\s*(?P<token>[A-Za-z0-9_-]+)"
                          r"(?P<reason>[^#]*)")


def suppression_on_line(src_line: str, token: str):
    """Parse an ``# al-lint:`` annotation on ``src_line`` for ``token``.
    Returns None (no annotation for this token) or the reason string
    (possibly empty — the caller must treat empty as a violation)."""
    for m in _SUPPRESS_RE.finditer(src_line):
        if m.group("token") == token:
            return m.group("reason").strip()
    return None


def apply_suppressions(findings, token, source_lines):
    """Resolve ``# al-lint: <token> <reason>`` annotations against a
    checker's findings.  ``source_lines`` maps repo-relative path -> list
    of source lines.  A finding whose line (or the line above it, for
    annotations placed on their own line) carries the token is marked
    suppressed with the reason; an empty reason converts the finding
    into a "suppression without a reason" violation instead.  Returns
    the findings list (mutated in place)."""
    if not token:
        return findings
    out = []
    for f in findings:
        lines = source_lines.get(f.path)
        reason = None
        if lines and f.line:
            for ln in (f.line, f.line - 1):
                if 1 <= ln <= len(lines):
                    reason = suppression_on_line(lines[ln - 1], token)
                    if reason is not None:
                        break
        if reason is None:
            out.append(f)
        elif reason:
            f.suppressed = True
            f.suppress_reason = reason
            out.append(f)
        else:
            out.append(Finding(
                check=f.check, path=f.path, line=f.line,
                message=(f"suppression '# al-lint: {token}' without a "
                         f"reason string (suppressing: {f.message})"),
                hint="every suppression carries a reason: "
                     f"# al-lint: {token} <why this is safe>"))
    findings[:] = out
    return findings


@dataclass
class Report:
    """One engine run: findings (live + suppressed), per-check counts,
    and the parse accounting that pins the single-parse contract."""

    findings: list = field(default_factory=list)
    checks_run: list = field(default_factory=list)
    files_scanned: int = 0
    parse_counts: dict = field(default_factory=dict)
    elapsed_s: float = 0.0

    @property
    def unsuppressed(self):
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self):
        return [f for f in self.findings if f.suppressed]

    def counts(self) -> dict:
        by_check: dict = {}
        for f in self.findings:
            entry = by_check.setdefault(f.check,
                                        {"findings": 0, "suppressed": 0})
            entry["suppressed" if f.suppressed else "findings"] += 1
        return by_check

    def to_json(self) -> dict:
        return {
            "checks_run": list(self.checks_run),
            "files_scanned": self.files_scanned,
            "max_parses_per_file": max(self.parse_counts.values(),
                                       default=0),
            "elapsed_s": round(self.elapsed_s, 3),
            "counts": self.counts(),
            "total_findings": len(self.unsuppressed),
            "total_suppressed": len(self.suppressed),
            "findings": [f.to_json() for f in self.findings],
        }
