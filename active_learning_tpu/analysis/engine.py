"""The analysis engine: one parse per file, many checkers over it.

The legacy ``scripts/trace_lint.py`` re-opened and re-parsed the package
once per check — 10 checks × ~80 files of redundant ``ast.parse``.  The
engine inverts that: an ``AstCache`` owns exactly one parse (and one
read) per file for the whole run, every checker receives the same
``Context``, and the cache COUNTS its parses so the single-parse
contract is an assertable property (tests/test_analysis.py pins
``max_parses_per_file <= 1`` and the <5 s whole-package wall).

Stdlib only, no jax import anywhere in this package: the lint must run
against a wedged, OOM'd, or backend-less tree (the same constraint the
status verb carries).

Adding a check (DESIGN.md §12): subclass ``Checker`` in
``analysis/checks/``, give it a unique ``id``, ``title``, and (if it
accepts suppressions) a ``suppress_token``, implement ``check(ctx)``
returning ``Finding``s, and append it to ``checks.CHECKERS``.  The CLI
(--list/--check) and the per-check fixture test pick it up from the
registry; a new check with no fixture under tests/fixtures/analysis/
fails the fixture-coverage test, so every checker lands with its golden
negative case.
"""

from __future__ import annotations

import ast
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

from .findings import Finding, Report, apply_suppressions

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PKG = os.path.join(REPO, "active_learning_tpu")

# The analyzers themselves are not analysis targets.
_SELF = ("trace_lint.py", "al_lint.py")


def default_files(repo: str = REPO) -> List[str]:
    """The whole-package file set: every .py under active_learning_tpu/,
    bench.py, and scripts/ (minus the lint entry points) — the same walk
    the legacy monolith did, so ported checks see the same tree."""
    pkg = os.path.join(repo, "active_learning_tpu")
    out: List[str] = []
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in sorted(files):
            if name.endswith(".py"):
                out.append(os.path.join(root, name))
    bench = os.path.join(repo, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    scripts = os.path.join(repo, "scripts")
    if os.path.isdir(scripts):
        for name in sorted(os.listdir(scripts)):
            if name.endswith(".py") and name not in _SELF:
                out.append(os.path.join(scripts, name))
    return out


class AstCache:
    """Parse-once cache: path -> (tree, error).  ``parse_counts`` records
    how many times each file was ACTUALLY read+parsed — the single-parse
    contract is asserted on it, not assumed."""

    def __init__(self):
        self._entries: Dict[str, Tuple[Optional[ast.AST],
                                       Optional[Exception]]] = {}
        self._sources: Dict[str, str] = {}
        self.parse_counts: Dict[str, int] = {}

    def get(self, path: str) -> Tuple[Optional[ast.AST],
                                      Optional[Exception]]:
        """(tree, None) on success, (None, exc) on read/parse failure —
        each checker formats the failure in its own message (the legacy
        checks' per-check wording survives the port)."""
        path = os.path.abspath(path)
        if path not in self._entries:
            self.parse_counts[path] = self.parse_counts.get(path, 0) + 1
            try:
                with open(path) as fh:
                    src = fh.read()
                self._sources[path] = src
                self._entries[path] = (ast.parse(src), None)
            except (OSError, SyntaxError) as exc:
                self._entries[path] = (None, exc)
        return self._entries[path]

    def source(self, path: str) -> str:
        """The cached source text ('' when unreadable).  Reads the file
        at most once, shared with the parse."""
        path = os.path.abspath(path)
        if path not in self._entries:
            self.get(path)
        return self._sources.get(path, "")


class Context:
    """Everything a checker sees: the file set, the shared cache, and
    repo-relative path helpers."""

    def __init__(self, files: Iterable[str], cache: Optional[AstCache] = None,
                 repo: str = REPO):
        self.repo = repo
        self.files = [os.path.abspath(f) for f in files]
        self.cache = cache or AstCache()

    def rel(self, path: str) -> str:
        return os.path.relpath(os.path.abspath(path), self.repo)

    def tree(self, path: str):
        return self.cache.get(path)

    def source_lines(self, path: str) -> List[str]:
        return self.cache.source(path).splitlines()


class Checker:
    """Plugin base.  Subclasses set ``id`` (unique, kebab-case — the
    --check selector and the fixture filename), ``title`` (one line for
    --list), ``suppress_token`` (None = no suppressions honored), and
    implement ``check(ctx) -> List[Finding]``."""

    id: str = ""
    title: str = ""
    suppress_token: Optional[str] = None

    def check(self, ctx: Context) -> List[Finding]:
        raise NotImplementedError

    def finding(self, ctx: Context, path: str, line: int, message: str,
                hint: str = "") -> Finding:
        return Finding(check=self.id, path=ctx.rel(path), line=line,
                       message=message, hint=hint)


class Engine:
    """Run a set of checkers over one shared-parse file set."""

    def __init__(self, files: Optional[Iterable[str]] = None,
                 repo: str = REPO):
        self.ctx = Context(files if files is not None
                           else default_files(repo), repo=repo)

    def run(self, checkers: Iterable[Checker],
            check_ids: Optional[Iterable[str]] = None) -> Report:
        wanted = set(check_ids) if check_ids else None
        selected = [c for c in checkers
                    if wanted is None or c.id in wanted]
        if wanted:
            unknown = wanted - {c.id for c in selected}
            if unknown:
                raise ValueError(
                    f"unknown check id(s): {', '.join(sorted(unknown))} "
                    f"(--list shows the registry)")
        t0 = time.perf_counter()
        report = Report(checks_run=[c.id for c in selected],
                        files_scanned=len(self.ctx.files))
        for checker in selected:
            found = checker.check(self.ctx)
            if checker.suppress_token and found:
                # Only the files that actually have findings need their
                # source lines — apply_suppressions never looks anywhere
                # else.
                flagged = {f.path for f in found}
                src_lines = {self.ctx.rel(p): self.ctx.source_lines(p)
                             for p in self.ctx.files
                             if self.ctx.rel(p) in flagged}
                apply_suppressions(found, checker.suppress_token,
                                   src_lines)
            report.findings.extend(found)
        report.parse_counts = dict(self.ctx.cache.parse_counts)
        report.elapsed_s = time.perf_counter() - t0
        return report
