"""The fleet layer: many experiments on preemptible capacity
(DESIGN.md §17).

PRs 4–16 built a complete preemption substrate — SIGTERM
checkpoint-and-exit, bit-identical resume from a kill at any point, the
atomic round journal, ``status --strict`` exit codes, per-run heartbeats
and Prometheus scrape files — and until now nothing consumed it: the
multi-experiment story was ``gen_jobs.py`` printing shell commands for a
human to paste.  This package is the layer above, after Podracer's
decoupled preemption-tolerant TPU actors:

  * ``spec``        — a declarative JSON sweep (strategy × seed ×
                      dataset × budget grids) expanded into run records
                      with stable run-ids;
  * ``journal``     — the atomic tmp+rename fleet journal (the
                      faults/journal.py discipline, one level up) the
                      controller restarts from;
  * ``controller``  — packs queued runs onto registered workers,
                      launches them through the existing CLI, polls
                      health through heartbeats / ``status --strict`` /
                      Prometheus scrape files, and reschedules preempted
                      runs with ``--resume_training``;
  * ``report``      — fleet-wide aggregation: every run's
                      run_report.json through the matched-budget
                      cross-run machinery (telemetry/report.py) plus a
                      merged fleet Prometheus scrape file;
  * ``cli``         — the ``fleet`` verb (``fleet run / status /
                      report``).

Host-pure BY CONSTRUCTION: no module in this package may import jax —
the controller runs on a CPU-only head node against workers it can never
share a backend with.  al_lint check 18 (``fleet-host-pure``) enforces
it statically, alongside the rule that every fleet-journal write goes
through the one atomic tmp+rename helper (``journal.write_atomic_json``).
Every fleet module declares ``_FLEET_MODULE = True`` — the closed
registry that same check audits for coverage.
"""

_FLEET_MODULE = True

from .controller import (FLEET_PROM_FILE, FleetController,  # noqa: F401
                         Worker, default_base_cmd, has_saved_experiment)
from .journal import (FLEET_JOURNAL_FILE, FleetJournal,  # noqa: F401
                      read_fleet_journal, write_atomic_json)
from .spec import (expand_spec, load_spec, run_argv,  # noqa: F401
                   run_id_for)
