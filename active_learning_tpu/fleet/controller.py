"""The fleet scheduler: N experiments packed onto preemptible workers.

The controller owns one fleet directory.  It expands a sweep spec into
run records (``spec.py``), persists every lifecycle transition in the
atomic fleet journal (``journal.py``), and packs queued runs onto
registered workers by free capacity — launching each through the
EXISTING CLI (``python -m active_learning_tpu`` as a localhost
subprocess; dry-run mode emits the commands for a real cluster's
launcher instead).  Health comes from the substrate PRs 4–16 built and
nothing previously consumed:

  * heartbeat files for liveness (mtime vs the embedded deadline);
  * ``status --strict`` exit codes — via ``status.strict_exit_code`` on
    the SAME summarize() the CLI uses, so controller and shell can
    never disagree about a run's health;
  * the per-run Prometheus scrape file for progress (rounds completed,
    fault_retries_total, degrade_events).

Failure modes, each named and tested (tests/test_fleet.py):

  * **worker dies / SIGKILL mid-round** — the child's exit code is
    non-zero; the run re-queues with ``--resume_training`` (when a saved
    experiment exists) up to ``max_attempts``, then parks as ``failed``;
  * **clean preemption (SIGTERM)** — the child checkpoints and exits 0
    with the round journal saying ``status=preempted``; the run
    re-queues for resume on the next free worker.  The bit-identical-
    resume contract (tests/test_faults.py) makes the fleet result
    provably identical to an unpreempted run;
  * **controller dies and restarts** — the fleet journal replays: runs
    whose pid is still alive with a fresh heartbeat are ADOPTED (polled
    to completion, never relaunched); dead ones re-queue for resume;
    finished ones stay finished;
  * **run degrades** — ``strict_exit_code`` 4 is recorded in the run's
    journal record and counted in the fleet gauges; the run keeps its
    worker (a self-healing run is progress, not a failure);
  * **run wedges (stale heartbeat)** — exit code 3: the child is killed
    and the run re-queues like any other preemption.

Host-pure: no jax import anywhere in this package (al_lint check 18) —
this process runs on a CPU-only head node that could never initialize a
worker's accelerator.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from ..faults.journal import JOURNAL_FILE, read_journal
from ..telemetry import prom
from ..telemetry.status import strict_exit_code, summarize
from .journal import FLEET_JOURNAL_FILE, FleetJournal
from .spec import expand_spec, run_argv

_FLEET_MODULE = True

# The saved-experiment marker files (experiment/resume.py spells these;
# redeclared here because importing experiment/ would drag jax onto the
# head node — tests pin the two spellings against resume.py's).
_STATE_FILE = "experiment_state.npz"
_META_FILE = "experiment_state.json"

FLEET_PROM_FILE = "fleet.prom"

# Run lifecycle states as journaled.  "preempted"/"stalled" are
# transitions, not states: the controller re-queues in the same poll, so
# the journal only ever shows queued/running/finished/failed.
RUN_STATES = ("queued", "running", "finished", "failed")

# Lock discipline: the controller is single-threaded by design (one
# poll loop; signals only set flags), so there is no _GUARDED_BY
# registry here — concurrency lives in the child processes.


def default_base_cmd() -> List[str]:
    return [sys.executable, "-m", "active_learning_tpu"]


def has_saved_experiment(ckpt_path: str, exp_name: str,
                         exp_hash: str) -> bool:
    """True when a resumable experiment state exists — the same
    two-file test experiment/resume.py applies, without the jax
    import."""
    state_dir = os.path.join(ckpt_path, f"{exp_name}_{exp_hash}")
    return (os.path.exists(os.path.join(state_dir, _STATE_FILE))
            and os.path.exists(os.path.join(state_dir, _META_FILE)))


class Worker:
    """One unit of capacity: a named slot group the scheduler packs runs
    onto.  On localhost every worker is this process's subprocess pool;
    ``env`` overlays the child environment (CI pins JAX_PLATFORMS=cpu
    here).  For a real cluster, dry-run mode emits the per-worker
    commands and an external launcher owns placement."""

    def __init__(self, name: str, slots: int = 1,
                 env: Optional[Dict[str, str]] = None):
        if slots < 1:
            raise ValueError(f"worker {name!r} needs at least one slot")
        self.name = name
        self.slots = slots
        self.env = dict(env or {})


class _Child:
    """A launched run: a real subprocess, or an ADOPTED pid from a
    previous controller life (same poll surface, no wait() rights)."""

    def __init__(self, pid: int, proc: Optional[subprocess.Popen] = None):
        self.pid = pid
        self.proc = proc

    def poll(self) -> Optional[int]:
        if self.proc is not None:
            return self.proc.poll()
        # Adopted: not our child, so no exit status — pid liveness is
        # the only signal, and the round journal supplies the verdict.
        try:
            os.kill(self.pid, 0)
            return None
        except OSError:
            return -1

    def adopted(self) -> bool:
        return self.proc is None

    def terminate(self) -> None:
        try:
            os.kill(self.pid, signal.SIGTERM)
        except OSError:
            pass

    def kill(self) -> None:
        try:
            os.kill(self.pid, signal.SIGKILL)
        except OSError:
            pass


class FleetController:
    """The scheduler.  ``schedule_once()`` is one poll: reap finished
    children, judge health, re-queue preemptions, pack free slots.
    ``run()`` loops it until every run is terminal (or ``stop()`` /
    SIGTERM asks for a clean handoff)."""

    def __init__(self, fleet_dir: str, spec: Dict[str, Any],
                 workers: List[Worker],
                 base_cmd: Optional[List[str]] = None,
                 max_attempts: int = 3, poll_every_s: float = 1.0,
                 dry_run: bool = False):
        self.fleet_dir = fleet_dir
        self.spec = spec
        self.workers = list(workers)
        if not self.workers and not dry_run:
            raise ValueError("a live fleet needs at least one worker")
        self.base_cmd = list(base_cmd or default_base_cmd())
        self.max_attempts = max_attempts
        self.poll_every_s = poll_every_s
        self.dry_run = dry_run
        self.journal = FleetJournal(
            os.path.join(fleet_dir, FLEET_JOURNAL_FILE))
        self._children: Dict[str, _Child] = {}
        self._stop_requested = False
        # Expand the spec, then replay the journal over it: run-ids are
        # stable (spec.run_id_for), so a restarted controller re-attaches
        # every lifecycle record to its run.
        self.runs: Dict[str, Dict[str, Any]] = {}
        for rec in expand_spec(spec):
            self.runs[rec["run_id"]] = {
                "run_id": rec["run_id"], "args": rec["args"],
                "state": "queued", "worker": None, "pid": None,
                "attempts": 0, "resumes": 0, "preemptions": 0,
                "health": None, "rc": None, "resume": False,
            }
        self._recover()

    # -- directories / commands -------------------------------------------

    def run_dir(self, run_id: str) -> str:
        return os.path.join(self.fleet_dir, "runs", run_id)

    def log_dir(self, run_id: str) -> str:
        return os.path.join(self.run_dir(run_id), "logs")

    def ckpt_dir(self, run_id: str) -> str:
        return os.path.join(self.run_dir(run_id), "ckpt")

    def prom_file(self, run_id: str) -> str:
        return os.path.join(self.run_dir(run_id), "run.prom")

    def command_for(self, run_id: str, resume: bool = False) -> List[str]:
        """The full launch argv for a run.  Controller-owned flags come
        AFTER the spec's (argparse last-wins), so the fleet layout —
        per-run log/ckpt dirs, deterministic exp identity, the scrape
        file — cannot be silently redirected by a spec entry."""
        run = self.runs[run_id]
        argv = self.base_cmd + run_argv(run["args"])
        argv += ["--exp_name", run["args"].get("exp_name", run_id),
                 "--exp_hash", "fleet",
                 "--log_dir", self.log_dir(run_id),
                 "--ckpt_path", self.ckpt_dir(run_id),
                 "--prometheus_file", self.prom_file(run_id)]
        if resume:
            argv.append("--resume_training")
        return argv

    def _can_resume(self, run_id: str) -> bool:
        run = self.runs[run_id]
        return has_saved_experiment(
            self.ckpt_dir(run_id),
            run["args"].get("exp_name", run_id), "fleet")

    # -- journal ----------------------------------------------------------

    def _journal_write(self, **extra: Any) -> None:
        snapshot = {
            rid: {k: run[k] for k in
                  ("state", "worker", "pid", "attempts", "resumes",
                   "preemptions", "health", "rc", "resume")}
            for rid, run in self.runs.items()}
        self.journal.write(
            spec_name=self.spec.get("name"), runs=snapshot,
            controller={"pid": os.getpid(),
                        "status": extra.pop("controller_status",
                                            "running")},
            **extra)

    def _recover(self) -> None:
        """Replay a previous controller life from the fleet journal:
        finished/failed records stick; a 'running' record whose pid is
        still alive with a non-stale heartbeat is ADOPTED; everything
        else re-queues (with resume when a saved experiment exists)."""
        from .journal import read_fleet_journal
        prior = read_fleet_journal(self.journal.path)
        if not prior:
            return
        for rid, old in (prior.get("runs") or {}).items():
            run = self.runs.get(rid)
            if run is None:
                continue  # the spec shrank; the journal keeps history
            run.update({k: old.get(k, run[k]) for k in
                        ("state", "worker", "pid", "attempts", "resumes",
                         "preemptions", "health", "rc", "resume")})
            if run["state"] == "running":
                child = _Child(run["pid"]) if run["pid"] else None
                if child is not None and child.poll() is None:
                    # Alive: ADOPT, never relaunch — a second process
                    # on the same ckpt dir would corrupt the run.  If
                    # it later proves wedged, the stale-heartbeat path
                    # kills and re-queues it like any other preemption.
                    self._children[rid] = child
                else:
                    self._requeue(rid, why="controller-restart")

    # -- scheduling -------------------------------------------------------

    def _requeue(self, run_id: str, why: str) -> None:
        run = self.runs[run_id]
        run["state"] = "queued"
        run["worker"] = None
        run["pid"] = None
        run["resume"] = self._can_resume(run_id)
        if why in ("preempted", "stalled"):
            run["preemptions"] += 1
        if run["resume"]:
            run["resumes"] += 1

    def _free_slots(self) -> List[Worker]:
        """Workers with spare capacity, one entry per free slot, in
        registration order — the packing is deterministic."""
        used: Dict[str, int] = {}
        for run in self.runs.values():
            if run["state"] == "running" and run["worker"]:
                used[run["worker"]] = used.get(run["worker"], 0) + 1
        slots = []
        for w in self.workers:
            for _ in range(w.slots - used.get(w.name, 0)):
                slots.append(w)
        return slots

    def _launch(self, run_id: str, worker: Worker) -> None:
        run = self.runs[run_id]
        resume = run["resume"] and self._can_resume(run_id)
        argv = self.command_for(run_id, resume=resume)
        os.makedirs(self.log_dir(run_id), exist_ok=True)
        os.makedirs(self.ckpt_dir(run_id), exist_ok=True)
        env = {**os.environ, **worker.env}
        out = open(os.path.join(self.run_dir(run_id), "child.log"), "ab")
        try:
            proc = subprocess.Popen(argv, stdout=out, stderr=out, env=env)
        finally:
            out.close()
        run.update(state="running", worker=worker.name, pid=proc.pid,
                   rc=None)
        run["attempts"] += 1
        self._children[run_id] = _Child(proc.pid, proc)

    def _reap(self, run_id: str, rc: int) -> None:
        """A child ended: the round journal — not the exit code alone —
        says what happened.  Clean preemption exits 0 with
        status=preempted; only status=finished (or no telemetry at all)
        with rc 0 counts as done."""
        run = self.runs[run_id]
        self._children.pop(run_id, None)
        run["rc"] = rc
        journal = read_journal(
            os.path.join(self.log_dir(run_id), JOURNAL_FILE)) or {}
        status = journal.get("status")
        if rc == 0 and status == "preempted":
            self._requeue(run_id, why="preempted")
        elif rc == 0:
            run.update(state="finished", worker=None, pid=None)
        elif run["attempts"] >= self.max_attempts:
            run.update(state="failed", worker=None, pid=None)
        else:
            self._requeue(run_id, why="died")

    def _poll_health(self, run_id: str) -> None:
        """Judge a running run through the status contract; a stale
        heartbeat (3) means the child wedged — kill it and let the reap
        path re-queue.  Degraded (4) is recorded, not acted on."""
        run = self.runs[run_id]
        run["health"] = strict_exit_code(summarize(self.log_dir(run_id)))
        if run["health"] == 3:
            child = self._children.get(run_id)
            if child is not None:
                child.kill()

    def progress_of(self, run_id: str) -> Dict[str, float]:
        """Rounds completed / fault retries / degrade events from the
        run's Prometheus scrape file — the third leg of the substrate,
        consumed as data."""
        try:
            with open(self.prom_file(run_id)) as fh:
                gauges = prom.parse(fh.read())
        except (OSError, ValueError):
            return {}
        out = {}
        for short, name in (("round", "al_run_round"),
                            ("fault_retries", "al_run_fault_retries_total"),
                            ("degrade_events", "al_run_degrade_events")):
            series = gauges.get(name)
            if series:
                out[short] = next(iter(series.values()))
        return out

    def counts(self) -> Dict[str, int]:
        c = {state: 0 for state in RUN_STATES}
        for run in self.runs.values():
            c[run["state"]] += 1
        return c

    def _write_fleet_prom(self) -> None:
        counts = self.counts()
        gauges: Dict[str, Any] = {
            f"runs_{state}": n for state, n in counts.items()}
        gauges["resumes_total"] = sum(
            r["resumes"] for r in self.runs.values())
        gauges["preemptions_total"] = sum(
            r["preemptions"] for r in self.runs.values())
        gauges["runs_degraded"] = sum(
            1 for r in self.runs.values()
            if r["state"] == "running" and r["health"] == 4)
        prom.write_textfile(
            os.path.join(self.fleet_dir, FLEET_PROM_FILE),
            prom.render(prom.gauge_samples(gauges, prefix="al_fleet_")))

    def schedule_once(self) -> List[List[str]]:
        """One scheduler poll.  Returns the commands launched this poll
        (in dry-run mode: the commands that WOULD launch, with the runs
        left queued — the cluster's own launcher owns them)."""
        # 1. Reap ended children.
        for rid in list(self._children):
            child = self._children[rid]
            rc = child.poll()
            if rc is not None:
                if child.adopted():
                    # No wait() rights on an adopted pid: the round
                    # journal is the only verdict.  finished → rc 0;
                    # anything else re-queues like a death.
                    journal = read_journal(os.path.join(
                        self.log_dir(rid), JOURNAL_FILE)) or {}
                    rc = 0 if journal.get("status") in ("finished",
                                                        "preempted") \
                        else 1
                self._reap(rid, rc)
        # 2. Health-check the survivors.
        for rid, run in self.runs.items():
            if run["state"] == "running" and rid in self._children:
                self._poll_health(rid)
        # 3. Pack queued runs onto free slots.
        launched: List[List[str]] = []
        queued = [rid for rid, run in sorted(self.runs.items())
                  if run["state"] == "queued"]
        if self.dry_run:
            launched = [self.command_for(rid, resume=self.runs[rid]
                        ["resume"] and self._can_resume(rid))
                        for rid in queued]
        else:
            for rid, worker in zip(queued, self._free_slots()):
                self._launch(rid, worker)
                launched.append(self.command_for(rid))
        self._journal_write()
        self._write_fleet_prom()
        return launched

    def done(self) -> bool:
        return all(run["state"] in ("finished", "failed")
                   for run in self.runs.values())

    def stop(self) -> None:
        self._stop_requested = True

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → clean handoff: stop scheduling, SIGTERM the
        children (they checkpoint-and-exit via their own handlers),
        journal ``controller=preempted``, return.  The next controller
        restarts from the journal and re-queues every unfinished run."""
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: self.stop())

    def run(self) -> Dict[str, int]:
        """Schedule until every run is terminal (or stop() is called).
        Returns the final state counts."""
        while True:
            self.schedule_once()
            if self.dry_run or self.done() or self._stop_requested:
                break
            time.sleep(self.poll_every_s)
        if self._stop_requested and not self.done():
            self._handoff()
        else:
            self._journal_write(
                controller_status="finished" if self.done() else "running")
        return self.counts()

    def _handoff(self) -> None:
        """The controller's own preemption: evict the children cleanly
        and journal the interrupted fleet for the next life."""
        for child in self._children.values():
            child.terminate()
        deadline = time.time() + 30.0
        for rid in list(self._children):
            child = self._children[rid]
            while child.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            rc = child.poll()
            if rc is None:
                child.kill()
                rc = -9
            self._reap(rid, rc)
        self._journal_write(controller_status="preempted")
        self._write_fleet_prom()
