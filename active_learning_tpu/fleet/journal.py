"""The atomic fleet journal: one JSON file recording where the FLEET is.

``fleet_journal.json`` lives in the fleet directory and carries the
controller's whole scheduling state — every run's lifecycle record
(state, worker, pid, attempts, resumes, progress), the controller's own
status, and a monotonic ``seq`` — rewritten atomically (tmp + rename,
the faults/journal.py discipline one level up) so a controller killed at
ANY point restarts from a complete, ordered record: a torn write leaves
the PREVIOUS complete journal on disk, never a spliced one.

``write_atomic_json`` is the ONE write path: al_lint check 18
(``fleet-host-pure``) statically forbids any other ``json.dump`` in the
fleet package, so a journal write that could tear cannot land.  The
``fleet_journal`` fault site sits inside it — enter point before the
tmp write, torn point between the tmp write and the rename — so the
chaos tests can MAKE the torn write happen and assert the reader sees
only complete payloads (tests/test_fleet.py).

Stdlib-only, like everything in this package: the journal must be
readable and writable from a CPU-only head node.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

from .. import faults

_FLEET_MODULE = True

FLEET_JOURNAL_FILE = "fleet_journal.json"

# Lock discipline, statically enforced (al_lint lock-discipline): the
# merged field dict and seq are mutated from the scheduler loop AND the
# signal-driven shutdown path — only under _lock.
_GUARDED_BY = {"_fields": "_lock", "_seq": "_lock"}


def write_atomic_json(path: str, payload: Dict[str, Any]) -> bool:
    """THE fleet-package JSON write: tmp + fsync-free rename (the
    publish_best idiom).  A crash before the rename leaves the previous
    complete file; a crash after is the new complete file.  Returns
    False instead of raising — a full disk must not take the controller
    down (the run children own the real progress)."""
    faults.site("fleet_journal")
    try:
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        # Torn point: a kill here leaves the complete tmp beside the
        # complete OLD journal — the reader never sees half a write.
        faults.site("fleet_journal", point="torn")
        os.replace(tmp, path)
    except OSError:
        return False
    return True


def read_fleet_journal(path: str) -> Optional[Dict[str, Any]]:
    """The journal payload, or None when absent/unparseable (a torn file
    is impossible by construction; missing means no controller ever ran
    in this fleet directory)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


class FleetJournal:
    """Merge-and-rewrite journal writer (the RoundJournal field
    semantics: a write merges its fields over the retained ones, None
    deletes).  Continues the ``seq`` of an existing file so two records
    can always be ordered across controller restarts — the monotonic tag
    never restarts within a fleet directory."""

    def __init__(self, path: str, enabled: bool = True):
        self.path = path
        self.enabled = enabled
        self._lock = threading.Lock()
        self._fields: Dict[str, Any] = {}
        prior = read_fleet_journal(path) if enabled else None
        self._seq = int(prior.get("seq", 0)) if prior else 0

    def write(self, **fields: Any) -> Optional[Dict[str, Any]]:
        """Merge ``fields`` (None values delete), bump seq, rewrite
        atomically through ``write_atomic_json``.  Returns the written
        payload (None when disabled or the write failed)."""
        if not self.enabled:
            return None
        with self._lock:
            for k, v in fields.items():
                if v is None:
                    self._fields.pop(k, None)
                else:
                    self._fields[k] = v
            self._seq += 1
            payload = {**self._fields, "seq": self._seq,
                       "ts": time.time()}
        return payload if write_atomic_json(self.path, payload) else None
