"""The ``fleet`` CLI verb: run / status / report.

    python -m active_learning_tpu fleet run --spec sweep.json \
        --fleet_dir ./fleet --workers w0,w1
    python -m active_learning_tpu fleet status --fleet_dir ./fleet
    python -m active_learning_tpu fleet report --fleet_dir ./fleet

``run`` drives a sweep to completion on localhost workers (or, with
``--dry_run``, prints the per-run commands for a real cluster's
launcher and exits — the controller never pretends to own remote
placement).  ``status`` is the lifecycle table from the fleet journal;
``report`` adds the matched-budget strategy comparison and rewrites the
merged fleet scrape file.  Host-pure like the rest of the package: the
head node never imports jax.
"""

from __future__ import annotations

import argparse
import json
from typing import List, Optional

from . import report as fleet_report
from .controller import FleetController, Worker, default_base_cmd
from .spec import load_spec

_FLEET_MODULE = True


def get_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m active_learning_tpu fleet",
        description="Run, inspect, and report a fleet of experiments")
    sub = p.add_subparsers(dest="verb", required=True)

    run = sub.add_parser(
        "run", help="drive a sweep spec to completion on local workers")
    run.add_argument("--spec", type=str, required=True,
                     help="sweep-spec JSON (gen_jobs --format fleet "
                          "emits the paper's grids in this shape)")
    run.add_argument("--fleet_dir", type=str, required=True,
                     help="fleet state root: journal, per-run dirs, "
                          "scrape files")
    run.add_argument("--workers", type=str, default="w0",
                     help="comma-separated worker names; name=N sets "
                          "slots (default 1), e.g. 'w0=2,w1'")
    run.add_argument("--max_attempts", type=int, default=3,
                     help="launches per run before it parks as failed")
    run.add_argument("--poll_every_s", type=float, default=1.0)
    run.add_argument("--dry_run", action="store_true",
                     help="print the per-run commands and exit without "
                          "launching (cluster-launcher mode)")
    run.add_argument("--base_cmd", type=str, default=None,
                     help="launcher prefix replacing 'python -m "
                          "active_learning_tpu' (shlex-split) — wrapper "
                          "scripts, srun/ssh shims, test harnesses")

    for verb, help_ in (("status", "lifecycle table from the journal"),
                        ("report", "fleet table + matched-budget "
                                   "comparison + merged scrape file")):
        sp = sub.add_parser(verb, help=help_)
        sp.add_argument("--fleet_dir", type=str, required=True)
        sp.add_argument("--json", action="store_true", dest="as_json")
    return p


def parse_workers(text: str) -> List[Worker]:
    workers = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, slots = part.partition("=")
        workers.append(Worker(name, int(slots) if slots else 1))
    return workers


def main(argv: Optional[List[str]] = None) -> int:
    args = get_parser().parse_args(argv)
    if args.verb == "run":
        import shlex
        spec = load_spec(args.spec)
        base_cmd = (shlex.split(args.base_cmd) if args.base_cmd
                    else default_base_cmd())
        controller = FleetController(
            args.fleet_dir, spec, parse_workers(args.workers),
            base_cmd=base_cmd,
            max_attempts=args.max_attempts,
            poll_every_s=args.poll_every_s, dry_run=args.dry_run)
        if args.dry_run:
            for cmd in controller.schedule_once():
                print(" ".join(cmd))
            return 0
        controller.install_signal_handlers()
        counts = controller.run()
        print("fleet run: " + "  ".join(
            f"{state}={n}" for state, n in sorted(counts.items())))
        # Non-zero only when a run EXHAUSTED its attempts; a clean
        # controller preemption (SIGTERM mid-schedule) exits 0 like a
        # preempted run does — the next life resumes from the journal.
        return 1 if counts.get("failed") else 0
    payload = fleet_report.fleet_payload(args.fleet_dir)
    if args.verb == "report":
        fleet_report.merge_prom(args.fleet_dir)
    if args.as_json:
        print(fleet_report.as_json(payload))
        return 0
    if args.verb == "status":
        public = {k: v for k, v in payload.items()
                  if k in ("spec_name", "controller", "counts",
                           "resumes_total", "preemptions_total")}
        print(f"fleet status: {args.fleet_dir}")
        print(json.dumps(public, indent=1))
        for rec in payload["runs"]:
            print(f"  {rec.get('run_id')}: {rec.get('state')} "
                  f"worker={rec.get('worker')} "
                  f"round={rec.get('round')} health={rec.get('health')}")
        return 0
    print(fleet_report.render_fleet(payload))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
