"""Fleet-wide reporting: every run's artifacts folded into one view.

Three outputs from one fleet directory:

  * the **fleet table** — per-run lifecycle (state, worker, attempts,
    resumes, health) from the fleet journal, plus rounds-completed and
    fault counters from each run's Prometheus scrape file;
  * the **matched-budget strategy comparison** — every finished run's
    ``run_report.json`` through the cross-run machinery in
    telemetry/report.py (PR 12), exactly the table ``report a b c``
    would render by hand;
  * the **merged fleet scrape file** — every run's ``al_run_*`` gauges
    relabeled with ``run_id`` into one exposition text beside the
    controller's own ``al_fleet_*`` gauges, so one node-exporter
    textfile covers the whole fleet.

Stdlib-only (host-pure), same contract as the status/report verbs: this
answers from any shell against a fleet directory, live or dead.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..telemetry import prom
from ..telemetry.report import compare_payload, load_run, render_compare
from .journal import FLEET_JOURNAL_FILE, read_fleet_journal

_FLEET_MODULE = True

MERGED_PROM_FILE = "fleet_runs.prom"


def fleet_runs(fleet_dir: str) -> List[str]:
    """The fleet's run-ids: the journal's record when present (ordering
    and history), else the runs/ directory listing (a journal lost to a
    dead disk must not hide the artifacts)."""
    journal = read_fleet_journal(
        os.path.join(fleet_dir, FLEET_JOURNAL_FILE))
    if journal and journal.get("runs"):
        return sorted(journal["runs"])
    return sorted(os.path.basename(d) for d in
                  glob.glob(os.path.join(fleet_dir, "runs", "*"))
                  if os.path.isdir(d))


def _run_progress(fleet_dir: str, run_id: str) -> Dict[str, Any]:
    """Rounds / fault retries / degrade events from the run's scrape
    file; empty when the run never wrote one."""
    path = os.path.join(fleet_dir, "runs", run_id, "run.prom")
    try:
        with open(path) as fh:
            gauges = prom.parse(fh.read())
    except (OSError, ValueError):
        return {}
    out: Dict[str, Any] = {}
    for short, name in (("round", "al_run_round"),
                        ("fault_retries", "al_run_fault_retries_total"),
                        ("degrade_events", "al_run_degrade_events")):
        series = gauges.get(name)
        if series:
            out[short] = next(iter(series.values()))
    return out


def fleet_payload(fleet_dir: str) -> Dict[str, Any]:
    """The machine-readable fleet report: journal lifecycle + per-run
    progress + the matched-budget comparison payload over every run
    with a report artifact."""
    journal = read_fleet_journal(
        os.path.join(fleet_dir, FLEET_JOURNAL_FILE)) or {}
    records = journal.get("runs") or {}
    rows = []
    reports = []
    for run_id in fleet_runs(fleet_dir):
        rec = dict(records.get(run_id) or {})
        rec["run_id"] = run_id
        rec.update(_run_progress(fleet_dir, run_id))
        rows.append(rec)
        run = load_run(os.path.join(fleet_dir, "runs", run_id, "logs"))
        if run is not None:
            run.setdefault("exp_name", run_id)
            reports.append(run)
    counts: Dict[str, int] = {}
    for rec in rows:
        state = rec.get("state") or "unknown"
        counts[state] = counts.get(state, 0) + 1
    return {"fleet_dir": fleet_dir,
            "spec_name": journal.get("spec_name"),
            "controller": journal.get("controller"),
            "seq": journal.get("seq"),
            "counts": counts,
            "resumes_total": sum(int(r.get("resumes") or 0)
                                 for r in rows),
            "preemptions_total": sum(int(r.get("preemptions") or 0)
                                     for r in rows),
            "runs": rows,
            "comparison": compare_payload(reports) if reports else None,
            "_reports": reports}


def render_fleet(payload: Dict[str, Any]) -> str:
    """The human fleet report: lifecycle table, then the matched-budget
    strategy comparison over every run that produced a report."""
    counts = payload["counts"]
    head = (f"fleet report: {payload.get('spec_name') or 'sweep'}  "
            f"({payload['fleet_dir']})\n"
            f"  runs: " + "  ".join(
                f"{state}={n}" for state, n in sorted(counts.items()))
            + f"  resumes={payload['resumes_total']}"
              f"  preemptions={payload['preemptions_total']}")
    headers = ["run_id", "state", "worker", "round", "attempts",
               "resumes", "health", "retries", "degrades"]
    lines = [head, "  ".join(headers)]
    for rec in payload["runs"]:
        cells = [rec.get("run_id"), rec.get("state"),
                 rec.get("worker"), rec.get("round"),
                 rec.get("attempts"), rec.get("resumes"),
                 rec.get("health"), rec.get("fault_retries"),
                 rec.get("degrade_events")]
        lines.append("  ".join(
            "-" if c is None else str(c) for c in cells))
    reports = payload.get("_reports") or []
    if reports:
        lines.append("")
        lines.append(render_compare(reports))
    else:
        lines.append("  (no run produced a run_report.json yet)")
    return "\n".join(lines)


def merge_prom(fleet_dir: str,
               out_file: Optional[str] = None) -> Tuple[str, int]:
    """Every run's scrape file merged into one exposition text: each
    ``al_run_*`` sample relabeled with ``run_id`` (existing labels
    kept), written atomically to ``fleet_runs.prom``.  Returns (path,
    runs merged)."""
    samples: List[prom.Sample] = []
    merged = 0
    for run_id in fleet_runs(fleet_dir):
        path = os.path.join(fleet_dir, "runs", run_id, "run.prom")
        try:
            with open(path) as fh:
                gauges = prom.parse(fh.read())
        except (OSError, ValueError):
            continue
        merged += 1
        for name, series in gauges.items():
            for labels, value in series.items():
                samples.append(
                    (name, {**dict(labels), "run_id": run_id}, value))
    out = out_file or os.path.join(fleet_dir, MERGED_PROM_FILE)
    prom.write_textfile(out, prom.render(samples))
    return out, merged


def as_json(payload: Dict[str, Any]) -> str:
    public = {k: v for k, v in payload.items() if not k.startswith("_")}
    return json.dumps(public, indent=1)
