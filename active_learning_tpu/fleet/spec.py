"""Sweep specs: the declarative form of a fleet of experiments.

A sweep spec is one JSON object describing N runs — ``gen_jobs.py``
reborn as a programmatic producer (``gen_jobs --format fleet`` emits the
paper's three grids in exactly this shape):

    {
      "name": "cifar10_paper",
      "defaults": {"dataset": "cifar10", "n_epoch": 200, ...},
      "grid":     {"strategy": ["MarginSampler", "RandomSampler"],
                   "run_seed": [0, 1]},
      "runs":     [{"strategy": "BADGESampler", "partitions": 10}]
    }

``expand_spec`` turns that into run records: the cartesian product of
the ``grid`` axes (in declaration order — JSON objects are ordered) plus
every explicit ``runs`` entry, each merged over ``defaults`` and stamped
with a STABLE run-id.  Stability is the contract the whole fleet layer
leans on: the id is a readable slug plus a content hash of the full
argument dict, so re-expanding the same spec after a controller restart
reproduces the same ids and the journal's lifecycle records re-attach to
their runs — and two specs that would launch an identical experiment
collide loudly instead of silently double-running it.

Arg dicts use CLI flag spellings without the dashes (``run_argv`` maps
them back: ``True`` → bare ``--flag``, ``False``/``None`` dropped), so a
spec round-trips through ``experiment/cli.get_parser`` — the controller
launches exactly what a human would have pasted.

Stdlib-only (host-pure): specs expand on a CPU-only head node.
"""

from __future__ import annotations

import hashlib
import itertools
import json
from typing import Any, Dict, List

_FLEET_MODULE = True

# Keys woven into the readable slug, in order, when present.
_SLUG_KEYS = ("strategy", "dataset", "round_budget", "run_seed")

# Keys a spec's top level may carry; anything else is a typo we refuse
# to guess about (a misspelled "grid" would silently launch one run).
_SPEC_KEYS = frozenset({"name", "defaults", "grid", "runs"})


def load_spec(path: str) -> Dict[str, Any]:
    """Read and validate a sweep-spec JSON file.  Raises ValueError on
    structural problems — a bad spec must die at submit time, not after
    half the fleet launched."""
    with open(path) as fh:
        spec = json.load(fh)
    return validate_spec(spec)


def validate_spec(spec: Dict[str, Any]) -> Dict[str, Any]:
    if not isinstance(spec, dict):
        raise ValueError("sweep spec must be a JSON object")
    unknown = set(spec) - _SPEC_KEYS
    if unknown:
        raise ValueError(
            f"sweep spec has unknown top-level keys {sorted(unknown)} "
            f"(allowed: {sorted(_SPEC_KEYS)})")
    if not isinstance(spec.get("defaults", {}), dict):
        raise ValueError("'defaults' must be an object of CLI args")
    grid = spec.get("grid", {})
    if not isinstance(grid, dict):
        raise ValueError("'grid' must be an object of {axis: [values]}")
    for axis, values in grid.items():
        if not isinstance(values, list) or not values:
            raise ValueError(
                f"grid axis {axis!r} must be a non-empty list")
    runs = spec.get("runs", [])
    if not isinstance(runs, list) \
            or any(not isinstance(r, dict) for r in runs):
        raise ValueError("'runs' must be a list of arg objects")
    if not grid and not runs:
        raise ValueError("sweep spec expands to zero runs "
                         "(empty 'grid' and 'runs')")
    return spec


def run_id_for(args: Dict[str, Any]) -> str:
    """A stable, readable id for one run: slug of the distinguishing
    args plus the first 8 hex chars of the sha1 of the FULL sorted arg
    dict.  Same args → same id across processes, restarts, and spec
    re-expansions; any differing arg → different id."""
    digest = hashlib.sha1(
        json.dumps(args, sort_keys=True, separators=(",", ":"),
                   default=str).encode()).hexdigest()[:8]
    slug = "-".join(str(args[k]) for k in _SLUG_KEYS if k in args)
    return f"{slug}-{digest}" if slug else digest


def expand_spec(spec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Expand a validated spec into run records
    ``{"run_id", "args"}`` — grid product first (axes iterate in
    declaration order, later axes fastest), then explicit ``runs``.
    Raises ValueError when two records collapse to the same run-id:
    identical args means an accidental double-launch of one experiment,
    and the journal (keyed by run-id) could not tell them apart."""
    validate_spec(spec)
    defaults = dict(spec.get("defaults", {}))
    records: List[Dict[str, Any]] = []
    grid = spec.get("grid", {})
    if grid:
        axes = list(grid.keys())
        for combo in itertools.product(*(grid[a] for a in axes)):
            args = {**defaults, **dict(zip(axes, combo))}
            records.append({"run_id": run_id_for(args), "args": args})
    for extra in spec.get("runs", []):
        args = {**defaults, **extra}
        records.append({"run_id": run_id_for(args), "args": args})
    seen: Dict[str, int] = {}
    for i, rec in enumerate(records):
        dup = seen.setdefault(rec["run_id"], i)
        if dup != i:
            raise ValueError(
                f"runs {dup} and {i} expand to identical args "
                f"(run_id {rec['run_id']}) — the sweep would launch "
                "the same experiment twice")
    return records


def run_argv(args: Dict[str, Any]) -> List[str]:
    """An arg dict as CLI tokens for ``python -m active_learning_tpu``:
    ``{"strategy": "MarginSampler", "freeze_feature": True}`` →
    ``["--strategy", "MarginSampler", "--freeze_feature"]``.  True means
    a bare store_true flag; False/None mean absent (argparse defaults
    apply); everything else is stringified."""
    argv: List[str] = []
    for key, value in args.items():
        if value is None or value is False:
            continue
        if value is True:
            argv.append(f"--{key}")
        else:
            argv.extend((f"--{key}", str(value)))
    return argv
