"""Device mesh + sharding helpers.

Replaces the reference's process-per-GPU DDP world (mp.spawn + NCCL process
group per round, src/query_strategies/strategy.py:288-336) with ONE
persistent JAX runtime and a `jax.sharding.Mesh`:

  * 1-D ``data`` axis today (the reference's only parallelism is data
    parallel, SURVEY.md §2), with the axis names kept open for model axes.
  * Batches are sharded over ``data``; parameters are replicated.  Under
    ``jit``'s automatic partitioning the gradient reduction and batch-norm
    statistics lower to XLA collectives over ICI — the DDP allreduce
    (strategy.py:336), metric all_gather (evaluation.py:69-98) and
    SyncBatchNorm (strategy.py:292) all fall out of the sharding annotations.
  * Multi-host pods: `initialize_distributed()` wires `jax.distributed`
    over DCN; the mesh then spans all processes' devices.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host init over DCN (no-op for single-process runs).

    The TPU equivalent of the reference's NCCL rendezvous
    (strategy.py:288-289,315) — but done once per run, not once per round.
    """
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)


def make_mesh(num_devices: int = -1,
              devices: Optional[Sequence[Any]] = None) -> Mesh:
    """1-D data-parallel mesh over the first ``num_devices`` devices
    (-1 = all).  Mirrors world_size = torch.cuda.device_count()
    (main_al.py:96)."""
    if devices is None:
        devices = jax.devices()
    if num_devices == -1:
        num_devices = len(devices)
    devices = np.asarray(devices[:num_devices])
    return Mesh(devices, (DATA_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dimension split across the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch: Dict[str, np.ndarray], mesh: Mesh) -> Dict[str, Any]:
    """Host batch -> device arrays with the batch axis sharded over the
    mesh.  This is the host->device boundary (the reference's pinned-memory
    H2D copies, strategy.py:264,328)."""
    sharding = batch_sharding(mesh)
    return {k: jax.device_put(v, sharding) for k, v in batch.items()}


def replicate(tree: Any, mesh: Mesh) -> Any:
    sharding = replicated_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
