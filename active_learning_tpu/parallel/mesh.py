"""Device mesh + sharding helpers.

Replaces the reference's process-per-GPU DDP world (mp.spawn + NCCL process
group per round, src/query_strategies/strategy.py:288-336) with ONE
persistent JAX runtime and a `jax.sharding.Mesh`:

  * 1-D ``data`` axis today (the reference's only parallelism is data
    parallel, SURVEY.md §2), with the axis names kept open for model axes.
  * Batches are sharded over ``data``; parameters are replicated.  Under
    ``jit``'s automatic partitioning the gradient reduction and batch-norm
    statistics lower to XLA collectives over ICI — the DDP allreduce
    (strategy.py:336), metric all_gather (evaluation.py:69-98) and
    SyncBatchNorm (strategy.py:292) all fall out of the sharding annotations.
  * Multi-host pods: `initialize_distributed()` wires `jax.distributed`
    over DCN; the mesh then spans all processes' devices.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import faults

DATA_AXIS = "data"


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host init over DCN (no-op for single-process runs).

    The TPU equivalent of the reference's NCCL rendezvous
    (strategy.py:288-289,315) — but done once per run, not once per round.
    Must run before any JAX backend initializes.  On a TPU pod slice pass
    just ``num_processes`` (the host count) and JAX auto-discovers the
    coordinator and process id; CPU/GPU clusters pass all three.  The CLI
    exposes --coordinator_address / --num_processes / --process_id.
    With no arguments at all this is a no-op (single-process run).
    """
    if num_processes is None and coordinator_address is None:
        return
    if num_processes is not None and num_processes <= 1:
        return
    # XLA:CPU cannot run cross-process computations on its default
    # (in-process) collectives — a 2-process CPU mesh dies at the first
    # jit with "Multiprocess computations aren't implemented on the CPU
    # backend".  The gloo implementation CAN, and it is how the pod-tier
    # contract is tested without hardware (the 2-process localhost
    # harness in tests/test_pod_tier.py).  Armed only when the
    # configured platform is CPU — read from the env var OR the jax
    # config knob (both settable without initializing a backend; a
    # jax.config.update("jax_platforms", "cpu") launch must arm too);
    # accelerators keep their native ICI/DCN collectives, and a jax too
    # old to know the knob just proceeds.
    spec = os.environ.get("JAX_PLATFORMS") or ""
    try:
        spec = jax.config.jax_platforms or spec
    except AttributeError:  # pragma: no cover - very old jax
        pass
    if spec.split(",")[0].strip().lower() == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover - jax-version-dependent
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)


def is_coordinator() -> bool:
    """True on the process that owns run-level side effects (checkpoint
    writes, metric sinks, audit files) — the reference's rank-0 guard
    (strategy.py:425-430)."""
    return jax.process_index() == 0


def is_multiprocess(mesh: Mesh) -> bool:
    """True when ``mesh`` spans devices of more than one process."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


class DispatchGate:
    """ONE enqueue order for collective-bearing dispatches when two host
    threads share a mesh (the pipelined round's speculative scorer +
    the trainer — experiment/pipeline.py, DESIGN.md §8).

    Used as a context manager around each jitted dispatch.  Two tiers of
    protection, matched to what each backend actually guarantees:

      * **Enqueue ordering (always).**  The lock makes every device see
        the two streams' computations enqueued in one global order.  On
        TPU that is sufficient: each core executes its enqueued programs
        in FIFO order, so collectives from different executables can
        never interleave across cores.
      * **Execution draining (``drain_mode``, CPU meshes only).**
        XLA:CPU does NOT preserve enqueue order at execution — device
        programs run on one shared thread pool, so computation A's
        program on core 2 can be parked behind computation B's while
        B's core-0 program waits on A's rendezvous: a cross-thread
        collective deadlock (observed live; two AllReduce run_ids
        mutually stuck).  When ``drain_mode`` is on, the dispatch site
        calls ``drain(out)`` BEFORE releasing the gate, so at most one
        collective-bearing computation is ever in flight.  The scorer
        arms it for exactly the window it shares the mesh
        (RoundPipeline.arm -> consume); single-threaded phases and
        sequential rounds never pay the sync.

    Reentrant so a dispatch site may nest helpers that also take the
    gate."""

    def __init__(self):
        self._lock = threading.RLock()
        # Flipped by the pipelined round on CPU meshes only; plain bool
        # write/read (atomic under the GIL).
        self.drain_mode = False
        # Per-thread seconds spent BLOCKED acquiring the gate — i.e.
        # stalled on the other stream's hold.  The overlap accounting
        # reads this to avoid claiming scorer time that actually
        # serialized with the train stream (and vice versa) as overlap.
        self._waits: Dict[int, float] = {}
        self._waits_lock = threading.Lock()

    def __enter__(self) -> "DispatchGate":
        # Fault point BEFORE the acquire: an injected failure here never
        # leaves the gate held (the `with` never entered).
        faults.site("dispatch")
        # Uncontended (and reentrant-by-holder) acquires take the fast
        # path: no clock read, no wait recorded.
        if not self._lock.acquire(blocking=False):
            t0 = time.perf_counter()
            self._lock.acquire()
            dt = time.perf_counter() - t0
            tid = threading.get_ident()
            with self._waits_lock:
                self._waits[tid] = self._waits.get(tid, 0.0) + dt
        return self

    def __exit__(self, *exc) -> bool:
        self._lock.release()
        return False

    def take_wait_s(self) -> float:
        """Seconds THIS thread spent blocked acquiring the gate since
        its last take (reset on read) — the contention the other
        stream's holds cost it."""
        with self._waits_lock:
            return self._waits.pop(threading.get_ident(), 0.0)

    def drain(self, tree: Any) -> Any:
        """Block until ``tree``'s arrays are computed — only in drain
        mode (see above); a no-op everywhere else, preserving the async
        dispatch the trainer's deferred loss materialization relies
        on.  Call while still HOLDING the gate."""
        if self.drain_mode:
            jax.block_until_ready(tree)
        return tree


def process_local_rows(mesh: Mesh, batch_size: int) -> slice:
    """The contiguous row range of a ``[batch_size, ...]`` batch (sharded
    over the data axis) owned by THIS process's devices.

    This is the per-host analogue of the reference's DistributedSampler
    rank slicing (strategy.py:312-314): each host feeds only its own rows,
    so a pod never decodes the full global batch per host.  Row ownership
    is read off the sharding itself, so it stays correct for any device
    order.  Single-process meshes own everything: slice(0, batch_size).
    """
    idx_map = batch_sharding(mesh).addressable_devices_indices_map(
        (batch_size,))
    if not idx_map:
        raise AssertionError(
            "this process owns no devices in the mesh — every process "
            "must contribute all its local devices (see make_mesh)")
    spans = []
    for idx in idx_map.values():
        s = idx[0]
        spans.append((s.start or 0,
                      batch_size if s.stop is None else s.stop))
    lo = min(s for s, _ in spans)
    hi = max(e for _, e in spans)
    if sum(e - s for s, e in spans) != hi - lo:
        raise AssertionError(
            f"process-local rows are not contiguous: {sorted(spans)}; "
            "the data axis must map each process to one contiguous block")
    return slice(lo, hi)


def process_pool_rows(mesh: Mesh, n_rows: int) -> slice:
    """The contiguous range of REAL pool rows [0, n_rows) owned by this
    process under the row-sharded layout — ``process_local_rows`` over
    the padded row count (``shard_rows`` pads to divide the mesh
    evenly), clamped back to the real rows.  The disk-pool backend
    (data/diskpool.py) reads only this range per host, the same
    per-process slicing ``shard_rows`` uploads through, so a pool never
    lands whole on any one host.  Single-process meshes own everything.
    """
    total = int(n_rows) + row_shard_pad(int(n_rows), mesh)
    local = process_local_rows(mesh, total)
    return slice(min(local.start, int(n_rows)), min(local.stop, int(n_rows)))


def make_mesh(num_devices: int = -1,
              devices: Optional[Sequence[Any]] = None) -> Mesh:
    """1-D data-parallel mesh over the first ``num_devices`` devices
    (-1 = all).  Mirrors world_size = torch.cuda.device_count()
    (main_al.py:96)."""
    if devices is None:
        devices = jax.devices()
    if num_devices == -1:
        num_devices = len(devices)
    if jax.process_count() > 1 and num_devices != len(devices):
        # Trimming would drop some processes' devices entirely — those
        # processes would own no rows of any batch and every collective
        # would deadlock or diverge.  Shrink the world, not the mesh.
        raise ValueError(
            f"num_devices={num_devices} would trim a {len(devices)}-device "
            "multi-host mesh; use fewer processes instead")
    devices = np.asarray(devices[:num_devices])
    return Mesh(devices, (DATA_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dimension split across the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (pool-row) dimension split across the data axis — the
    resident-pool layout of DESIGN.md §2b.  Identical to batch_sharding
    in spec; named separately because the two axes mean different
    things: a batch is transient per step, pool rows are pinned for the
    experiment and their per-chip HBM cost is ``nbytes / num_devices``.
    """
    return NamedSharding(mesh, P(DATA_AXIS))


def row_shard_pad(n: int, mesh: Mesh) -> int:
    """Rows of zero-padding needed to split ``n`` rows evenly over the
    mesh's data axis (row-sharded uploads pad; consumers only ever
    index real rows)."""
    return (-n) % mesh.devices.size


def shard_rows(array: np.ndarray, mesh: Mesh,
               rows: Optional[int] = None) -> Any:
    """Host array -> device array with the leading (row) axis sharded
    over the data axis, zero-padded to ``rows`` total rows (default: the
    array's own length), rounded up to divide evenly.  Built per shard
    (``jax.make_array_from_callback``): each device's row block is
    sliced — and only the tail shard's pad materialized — right before
    its own H2D copy, so the full array never exists padded on host and
    never lands whole on any single device.  That bounds the transient
    host overhead at one shard instead of one pool: a 10.5 GB factor
    matrix costs ~10.5/ndev GB of working copy, not a second 10.5 GB,
    and 10.5/ndev GB per chip once resident.

    Multi-process meshes (the pod tier, DESIGN.md §15): per-process
    shard assembly via ``jax.make_array_from_process_local_data`` —
    each host slices and uploads ONLY its own contiguous row range of
    the global array, so the full pool never lands whole on any one
    host either; the assembled array is identical to the single-process
    layout shard for shard.  ``array`` may be any host sequence that
    slices to the local range (an in-memory pool, a memmap, a
    shard-serving reader) — only the local rows are ever touched."""
    faults.site("shard_upload")
    n = array.shape[0]
    total = n if rows is None else int(rows)
    if total < n:
        raise ValueError(f"rows={total} < array rows {n}")
    total += row_shard_pad(total, mesh)
    tail = array.shape[1:]

    def _block(lo: int, hi: int) -> np.ndarray:
        # Per-shard fault point: one block's H2D can fail while its
        # siblings succeed (the caller's RetryPolicy re-runs the upload).
        faults.site("shard_upload", point="torn")
        block = np.ascontiguousarray(array[lo:min(hi, n)])
        short = (hi - lo) - block.shape[0]
        if short:
            block = np.concatenate(
                [block, np.zeros((short, *tail), array.dtype)])
        return block

    if is_multiprocess(mesh):
        local = process_local_rows(mesh, total)
        return jax.make_array_from_process_local_data(
            row_sharding(mesh), _block(local.start, local.stop),
            (total, *tail))

    def _shard(index):
        rs = index[0]
        lo = rs.start or 0
        return _block(lo, total if rs.stop is None else rs.stop)

    return jax.make_array_from_callback(
        (total, *tail), row_sharding(mesh), _shard)


def owner_rows(arr: Any, idxs: Any, axis: str = DATA_AXIS) -> Any:
    """Inside a ``shard_map`` body over ``axis``: rows of the shard-local
    ``arr`` for GLOBAL row indices ``idxs`` [K], assembled from their
    owning shards by masked psum.  THE exactness-critical primitive of
    the row-sharded pool, shared by ``resident.sharded_pool_gather`` and
    the k-center collective backend's center-row gather: exactly one
    shard owns each global index, non-owners contribute exact zeros, so
    the sum is the owner's value bit for bit (uint8 included) — the
    invariant every pick/score/batch-identity test rests on.  Out-of-
    range indices (pad rows past the last shard) clip to existing rows
    but are owned by nobody, so they come back as zeros."""
    rows = arr.shape[0]
    off = (jax.lax.axis_index(axis) * rows).astype(idxs.dtype)
    loc = jnp.clip(idxs - off, 0, rows - 1)
    mine = (idxs >= off) & (idxs < off + rows)
    picked = jnp.where(mine.reshape((-1,) + (1,) * (arr.ndim - 1)),
                       arr[loc], jnp.zeros((), arr.dtype))
    return jax.lax.psum(picked, axis)


def owner_rows_scattered(arr: Any, idxs: Any, axis: str = DATA_AXIS) -> Any:
    """``owner_rows``' reduce-scatter twin: rows of the shard-local
    ``arr`` for GLOBAL row indices ``idxs`` [K] (REPLICATED — every
    shard passes the same vector), assembled from their owning shards
    and SCATTERED — shard i receives rows [i*K/ndev, (i+1)*K/ndev) of
    the result instead of the full [K].  Exact for the same reason
    owner_rows is (each element sums exactly one owner value plus
    zeros — any reduction order is the owner's bits), at 1/ndev the
    wire of the full psum broadcast.  The ring column feed seeds each
    shard's starting center block with this (strategies/kcenter.py);
    like owner_rows, this is the ONE spelling of the masked-scatter
    idiom (al_lint collective-axis).  K must divide the mesh."""
    rows = arr.shape[0]
    off = (jax.lax.axis_index(axis) * rows).astype(idxs.dtype)
    loc = jnp.clip(idxs - off, 0, rows - 1)
    mine = (idxs >= off) & (idxs < off + rows)
    picked = jnp.where(mine.reshape((-1,) + (1,) * (arr.ndim - 1)),
                       arr[loc], jnp.zeros((), arr.dtype))
    return jax.lax.psum_scatter(picked, axis, scatter_dimension=0,
                                tiled=True)


def ring_shift(tree: Any, ndev: int, axis: str = DATA_AXIS) -> Any:
    """THE ring-permute column-feed primitive — the ONE spelling of the
    ring-feed idiom (statically enforced: al_lint collective-axis allows
    a ring-perm ``ppermute`` only here).  Inside a ``shard_map`` body
    over ``axis``: rotate each shard's block to its RIGHT neighbor
    (shard i's block lands on shard (i+1) % ndev), so after ndev
    successive shifts every shard has held every other shard's block
    exactly once and the blocks are home again.  This is SNIPPETS.md
    [1]'s classic TPU ring pattern spelled with ``lax.ppermute`` (XLA
    lowers it to collective-permute on the ICI ring) instead of a
    hand-rolled Pallas DMA — same wire schedule, composes under jit and
    ``lax.fori_loop``.

    The k-center initial-min/minimax scans fold distance strips over the
    rotating blocks (strategies/kcenter.py): each hop moves one block of
    labeled-center columns between neighbors instead of uploading host
    column blocks and broadcasting them to every device — min/max folds
    over the rotating blocks are exact, so consumers stay bit-identical
    to the replicated column scans.  ``ndev`` must be the mesh's static
    device count (the permutation is a trace-time constant)."""
    perm = [(i, (i + 1) % ndev) for i in range(ndev)]
    return jax.tree.map(
        lambda x: jax.lax.ppermute(x, axis, perm=perm), tree)


def is_row_sharded(array: Any) -> bool:
    """True when a device array's leading axis is split over a mesh axis
    (the row-sharded pool layout), read off the committed sharding —
    host-side introspection, never valid on tracers."""
    spec = getattr(getattr(array, "sharding", None), "spec", None)
    return bool(spec) and spec[0] is not None


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# -- quantized gradient sync (DESIGN.md §4 + §15, "The gradient path") ----

GRAD_ALLREDUCE_MODES = ("f32", "int8", "int8_rs", "auto")

# Elements per quantization block: one f32 scale amortized over 256
# int8 payload bytes (~1.6% scale overhead), small enough that a block
# shares one dynamic range (EQuARX's block-scaling argument: per-tensor
# scales clip outlier-heavy gradients; per-block ones track them).
INT8_BLOCK = 256

# The wire-form crossover (documented since PR 9, now acted on): the
# all_gather-shaped int8_allreduce moves (ndev-1)*n int8 bytes per
# device — a real win over the ~8n-byte f32 ring psum through 8
# devices, INVERTED past ~9.  Above this device count the int8 path
# switches to the reduce-scatter wire form (~2n bytes, ndev-free).
INT8_WIRE_CROSSOVER_NDEV = 8

INT8_WIRE_FORMS = ("allgather", "reduce_scatter")


def resolve_grad_allreduce(mode: str, mesh: Mesh) -> str:
    """The ONE rule for which gradient-sync path a Trainer builds:
    quantized sync (``int8``/``int8_rs``/``auto``) only on multi-device
    meshes (a single device has no wire to save — the quantization
    would cost accuracy for nothing); anything else is the
    partitioner's bit-exact f32 psum.  Returns "f32" or "int8" — the
    WIRE form within int8 (all-gather vs reduce-scatter) is a separate
    resolution, ``resolve_int8_wire``."""
    if mode not in GRAD_ALLREDUCE_MODES:
        raise ValueError(f"grad_allreduce={mode!r} is not one of "
                         f"{'/'.join(GRAD_ALLREDUCE_MODES)}")
    if mesh.devices.size <= 1:
        return "f32"
    if mode in ("int8", "int8_rs", "auto"):
        return "int8"
    return mode


def resolve_int8_wire(mode: str, mesh: Mesh) -> str:
    """Which WIRE the quantized gradient sync uses, from the requested
    mode + the mesh: ``int8_rs`` forces the reduce-scatter form (tests,
    A/B captures); ``int8``/``auto`` pick reduce-scatter above the
    documented ~8-device crossover and keep the proven all-gather form
    on 2-8 device meshes (where (ndev-1)*n < 8n already wins and one
    quantization round-trip beats two).  Meaningless for f32 — callers
    gate on ``resolve_grad_allreduce`` first."""
    if mode == "int8_rs":
        return "reduce_scatter"
    if mesh.devices.size > INT8_WIRE_CROSSOVER_NDEV:
        return "reduce_scatter"
    return "allgather"


def wire_model_bytes(form: str, ndev: int, n: int,
                     block: int = INT8_BLOCK) -> int:
    """Per-device wire bytes to sync one ``n``-element f32 gradient
    tree, by form — the pod-tier wire-model table (DESIGN.md §15),
    cross-checked against measured ``collective_bytes_total`` in
    tests/test_pod_tier.py:

      ``f32``            ring all-reduce: reduce-scatter + all-gather
                         passes, ~2 * 4n * (ndev-1)/ndev  (~8n);
      ``allgather``      PR 9's int8_allreduce: every device receives
                         every other device's quantized payload —
                         (ndev-1) * (n + 4n/block) int8+scale bytes,
                         LINEAR in ndev (the documented blowup);
      ``reduce_scatter`` the EQuARX-shaped form: all_to_all of the
                         quantized shards + all_gather of the
                         re-quantized reduced shards, each moving
                         (ndev-1)/ndev * (n + 4n/block) — ~2n total,
                         ndev-free.
    """
    if ndev <= 1:
        return 0
    scale_bytes = 4 * -(-n // block)
    if form == "f32":
        return int(2 * 4 * n * (ndev - 1) / ndev)
    if form == "allgather":
        return (ndev - 1) * (n + scale_bytes)
    if form == "reduce_scatter":
        return int(2 * (n + scale_bytes) * (ndev - 1) / ndev)
    raise ValueError(f"unknown wire form {form!r}")


def int8_allreduce(tree: Any, axis: str = DATA_AXIS,
                   block: int = INT8_BLOCK) -> Any:
    """EQuARX-style block-scaled int8 gradient all-reduce, inside a
    ``shard_map`` body over ``axis``: each device quantizes its local
    gradients to int8 against a SHARED per-block scale (pmax of the
    local absmax — every device must use one scale or the sums don't
    commute), the collective moves the int8 payload, and each device
    de-quantizes after a float32-accumulated local sum.

    Wire model, honestly: this is the all_gather-then-local-sum form —
    the only quantized reduction expressible in today's XLA ops (EQuARX
    itself requantizes inside a modified ring all-reduce, which is not
    user-expressible).  Per device it moves ``(ndev-1) * n`` int8 bytes
    vs a ring f32 psum's ``~2 * 4 * n``, so the wire win is
    ``8/(ndev-1)``: ~4x at 2-4 devices, still >1 through 8 (the
    single-process single-host meshes this path targets today), and
    INVERTED past ~9 devices — pod-scale needs a quantized
    reduce-scatter and is deliberately out of scope (the auto rules
    never pick int8 there: it is flag-only and the flag is default-off).

    Deterministic and bounded: with a shared scale, round-to-nearest
    per element, and an exact f32 sum of <=127-magnitude integers, the
    result is identical on every device and the per-element error is
    bounded by ``ndev * scale / 2`` with ``scale = blockmax / 127`` —
    the delta the learning probe and tests/test_backward.py pin.  A
    non-finite block (loss spike) poisons to NaN instead of quantizing
    to garbage, so blow-ups stay as visible as on the f32 path.
    Non-float leaves psum exactly.
    """
    def one(x):
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return jax.lax.psum(x, axis)
        shape, dtype = x.shape, x.dtype
        flat = x.reshape(-1).astype(jnp.float32)
        n = flat.shape[0]
        pad = (-n) % block
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        blocks = flat.reshape(-1, block)
        absmax = jax.lax.pmax(jnp.max(jnp.abs(blocks), axis=1), axis)
        scale = jnp.maximum(absmax, jnp.float32(1e-30)) / 127.0
        q = jnp.clip(jnp.round(blocks / scale[:, None]),
                     -127, 127).astype(jnp.int8)
        # int8 on the wire; the sum accumulates f32 AFTER the gather
        # (summing in int8 would wrap past ndev=2).
        gathered = jax.lax.all_gather(q, axis)
        total = jnp.sum(gathered.astype(jnp.float32), axis=0)
        # Non-finite gradients must SURFACE, exactly as the f32 psum
        # would surface them: an inf/NaN block's scale is non-finite
        # and round(x/inf)=0 would silently launder the blow-up into a
        # zero gradient — poison the whole block to NaN instead so the
        # grad-norm telemetry and any NaN guard still see it.
        out = jnp.where(jnp.isfinite(absmax)[:, None],
                        total * scale[:, None], jnp.float32(jnp.nan))
        out = out.reshape(-1)
        if pad:
            out = out[:n]
        return out.reshape(shape).astype(dtype)

    return jax.tree.map(one, tree)


def int8_reduce_scatter(tree: Any, ndev: int, axis: str = DATA_AXIS,
                        block: int = INT8_BLOCK) -> Any:
    """The pod-tier quantized gradient sync (DESIGN.md §15): EQuARX-
    shaped block-scaled int8 REDUCE-SCATTER + all-gather of the
    re-quantized reduced shards, inside a ``shard_map`` body over
    ``axis``.  Fixes ``int8_allreduce``'s documented wire blowup — that
    form moves ``(ndev-1) * n`` int8 bytes per device (every device
    receives every other device's payload), inverted vs the ~8n f32
    ring psum past ~9 devices; this one moves ``~2n`` regardless of
    ndev (``wire_model_bytes``), which is why ``resolve_int8_wire``
    auto-selects it above the crossover.

    Wire schedule, per leaf:

      1. quantize the local gradient to int8 against a SHARED per-block
         scale (pmax of the block absmax — sums must commute);
      2. ``all_to_all`` the quantized payload: each device sends shard
         j of its blocks to device j and receives ITS shard from every
         peer — ``(ndev-1)/ndev * n`` int8 bytes, the reduce-scatter
         leg (XLA exposes no requantizing reduce-scatter op; EQuARX
         requantizes inside a modified ring, which is not
         user-expressible — all_to_all + local f32 sum is the same
         bytes with the sum hoisted to the shard owner);
      3. each shard owner accumulates its slice in float32 and
         RE-QUANTIZES it against its own fresh per-block scale;
      4. ``all_gather`` the quantized reduced shards + their scales —
         ``(ndev-1)/ndev * n`` int8 bytes + the ~1.6% scale sidecar —
         and dequantize.

    Deterministic and replicated: every device dequantizes the SAME
    owner-produced bytes, and the f32 accumulation order over the
    device axis is fixed — the result is identical on every device.
    Bounded error: first quantization contributes <= ndev * scale1 / 2
    per element (scale1 = blockmax/127), the requantization another
    scale2 / 2 — one quantization round-trip more than the all-gather
    form, which is why the 2-8 device meshes keep that form and why
    BOTH sit behind the same learning probe (driver.
    run_grad_allreduce_probe probes whichever form the mesh resolves).
    Non-finite blocks poison to NaN exactly like ``int8_allreduce``.
    ``ndev`` must be the mesh's static device count."""
    def one(x):
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return jax.lax.psum(x, axis)
        shape, dtype = x.shape, x.dtype
        flat = x.reshape(-1).astype(jnp.float32)
        n = flat.shape[0]
        pad = (-n) % (block * ndev)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        blocks = flat.reshape(-1, block)
        nb = blocks.shape[0]
        m = nb // ndev
        absmax = jax.lax.pmax(jnp.max(jnp.abs(blocks), axis=1), axis)
        scale = jnp.maximum(absmax, jnp.float32(1e-30)) / 127.0
        q = jnp.clip(jnp.round(blocks / scale[:, None]),
                     -127, 127).astype(jnp.int8)
        # Reduce-scatter leg: int8 on the wire, each device ends up
        # holding every peer's copy of ITS m-block shard.
        recv = jax.lax.all_to_all(q.reshape(ndev, m, block), axis,
                                  split_axis=0, concat_axis=0)
        me = jax.lax.axis_index(axis)
        # The shared scale vector is replicated math, so slicing my
        # shard of it is local; the f32 sum over the device axis is the
        # exact sum of <=127-magnitude integers times one scale.
        my_scale = jax.lax.dynamic_slice_in_dim(
            scale.reshape(ndev, m), me, 1, 0)[0]
        reduced = jnp.sum(recv.astype(jnp.float32), axis=0) \
            * my_scale[:, None]
        absmax2 = jnp.max(jnp.abs(reduced), axis=1)
        scale2 = jnp.maximum(absmax2, jnp.float32(1e-30)) / 127.0
        q2 = jnp.clip(jnp.round(reduced / scale2[:, None]),
                      -127, 127).astype(jnp.int8)
        # All-gather leg: quantized reduced shards + the scale sidecar.
        gathered = jax.lax.all_gather(q2, axis)
        scales = jax.lax.all_gather(scale2, axis)
        out = gathered.astype(jnp.float32) * scales[:, :, None]
        # Same poison rule as int8_allreduce: a non-finite block must
        # SURFACE as NaN, never launder into a zero gradient.
        out = jnp.where(jnp.isfinite(absmax).reshape(ndev, m)[:, :, None],
                        out, jnp.float32(jnp.nan))
        out = out.reshape(-1)
        if pad:
            out = out[:n]
        return out.reshape(shape).astype(dtype)

    return jax.tree.map(one, tree)


def shard_batch(batch: Dict[str, np.ndarray], mesh: Mesh) -> Dict[str, Any]:
    """Host batch -> device arrays with the batch axis sharded over the
    mesh.  This is the host->device boundary (the reference's pinned-memory
    H2D copies, strategy.py:264,328).

    Single-process: ``batch`` holds the full global batch.  Multi-process:
    every process passes ONLY its ``process_local_rows`` slice and the
    global array is assembled across hosts — the data-parallel contract of
    the reference's per-rank DataLoader (strategy.py:325-328) without any
    cross-host copy of example data.
    """
    sharding = batch_sharding(mesh)
    if not is_multiprocess(mesh):
        return {k: jax.device_put(v, sharding) for k, v in batch.items()}
    n_local = mesh.local_mesh.devices.size
    scale = mesh.devices.size // n_local
    return {
        k: jax.make_array_from_process_local_data(
            sharding, np.asarray(v), (v.shape[0] * scale, *v.shape[1:]))
        for k, v in batch.items()
    }


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Copy a host pytree to every device (every process passes the same
    values — the usual multi-controller contract)."""
    sharding = replicated_sharding(mesh)
    if not is_multiprocess(mesh):
        return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sharding,
                                            lambda idx: x[idx])

    return jax.tree.map(put, tree)


def fetch(tree: Any, mesh: Optional[Mesh] = None) -> Any:
    """Device pytree -> host numpy, working for batch-sharded outputs on
    multi-host meshes too (each process sees the full global array — the
    reference's dist.all_gather of eval/score results, evaluation.py:69-98).
    Fully-replicated outputs (losses, metric counts) are fetched directly.
    """
    if mesh is None or not is_multiprocess(mesh):
        return jax.tree.map(np.asarray, tree)
    from jax.experimental import multihost_utils

    def one(x):
        if getattr(x, "is_fully_replicated", True):
            return np.asarray(x)
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))

    return jax.tree.map(one, tree)
