"""Device-resident pools: in-memory dataset rows uploaded once per
experiment and gathered ON DEVICE per batch.

One cache serves every consumer — acquisition scoring
(strategies/scoring.py) and evaluation (train/trainer.py) — so a pool
whose views share storage (ArrayDataset.with_view) is uploaded exactly
once, and the ``resident_scoring_bytes`` budget means what it says per
underlying array.  Entries retain their dataset object: keys include
id()s, and without the reference a recycled id could silently alias
another pool's images.

Layout of a cache dict:
  cache["images"][(id(images), n)] = (dataset, images_dev, labels_dev)
  cache["steps"][(id(step_fn), with_labels)] = jitted runner

Virtual-CPU-mesh caveat: the N replicas' on-device gathers execute
serially on one core there, so resident paths can measure slower on the
test mesh; on real chips the replicas are parallel and the gather
replaces a host->device transfer.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import numpy as np

from . import mesh as mesh_lib


def eligible(dataset: Any, max_bytes: int) -> bool:
    """In-memory (ArrayDataset-style) and within the byte budget."""
    images = getattr(dataset, "images", None)
    return (max_bytes > 0 and isinstance(images, np.ndarray)
            and images[: len(dataset)].nbytes <= max_bytes)


def pool_arrays(cache: Dict, dataset: Any, mesh) -> Tuple[Any, Any]:
    """(images_dev, labels_dev) for the dataset, uploaded once per
    (underlying array, length) — views sharing storage share the upload.
    replicate() device_puts EXPLICITLY (transfer-guard friendly)."""
    images = cache.setdefault("images", {})
    n = len(dataset)
    key = (id(dataset.images), n)
    if key not in images:
        images[key] = (
            dataset,
            mesh_lib.replicate(
                np.ascontiguousarray(dataset.images[:n]), mesh),
            mesh_lib.replicate(
                dataset.targets[:n].astype(np.int32), mesh))
    return images[key][1], images[key][2]


def get_runner(cache: Dict, step_fn: Callable, mesh,
               with_labels: bool = False) -> Callable:
    """Jitted gather+step over a resident pool: rows are picked out on
    device and constrained to the batch sharding, so each batch costs one
    tiny [batch]-int32 transfer instead of the image rows."""
    steps = cache.setdefault("steps", {})
    key = (id(step_fn), with_labels)
    if key not in steps:
        batch_sharding = mesh_lib.batch_sharding(mesh)

        if with_labels:

            @jax.jit
            def run(variables, images, labels, ids, mask):
                batch = {
                    "image": jax.lax.with_sharding_constraint(
                        images[ids], batch_sharding),
                    "label": labels[ids],
                    "mask": mask,
                }
                return step_fn(variables, batch)
        else:

            @jax.jit
            def run(variables, images, ids, mask):
                batch = {
                    "image": jax.lax.with_sharding_constraint(
                        images[ids], batch_sharding),
                    "mask": mask,
                }
                return step_fn(variables, batch)

        steps[key] = run
    return steps[key]
