"""Device-resident pools: in-memory dataset rows uploaded once per
experiment and gathered ON DEVICE per batch.

One cache serves every consumer — acquisition scoring
(strategies/scoring.py), evaluation, AND the trainer's resident-gather
train feed (train/trainer.py) — so a pool whose views share storage
(ArrayDataset.with_view) is uploaded exactly once and that single pinned
array feeds scoring, validation, and training.  The byte budget is
accounted across the WHOLE cache: ``eligible`` admits a new array only
when it fits alongside everything already pinned, so "one pinned pool
serves both scoring and training" is also one set of bytes in the
budget, never two.  Entries retain their dataset object: keys include
id()s, and without the reference a recycled id could silently alias
another pool's images.

Layout of a cache dict:
  cache["images"][(id(images), n)] = (dataset, images_dev, labels_dev)
  cache["steps"][(id(step_fn), with_labels)] = jitted runner
  cache["lru"] = [key, ...]  # least-recently-used first (eviction order)

Virtual-CPU-mesh caveat: the N replicas' on-device gathers execute
serially on one core there, so resident paths can measure slower on the
test mesh; on real chips the replicas are parallel and the gather
replaces a host->device transfer.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from . import mesh as mesh_lib
from ..utils.logging import get_logger

# HBM held back from the auto-sized resident budget: training activations,
# XLA workspace, and the model/optimizer trees all coexist with a pinned
# pool.  4 GB covers the ResNet-50 224px train step at 256 rows/chip
# (bf16 activations ~26 KB/row/layer-group, measured envelope well under
# 3 GB) with headroom for compile-time scratch.
AUTO_RESERVE_BYTES = 4 << 30


def auto_budget(reserve_bytes: int = AUTO_RESERVE_BYTES,
                stats: Optional[Dict[str, int]] = None,
                pinned: int = 0) -> int:
    """Size the device-resident pool budget from LIVE HBM headroom:
    (bytes_limit − bytes_in_use) − reserve, floored at 0.

    ``pinned``: bytes ALREADY pinned in the caller's resident cache.
    Live headroom has those bytes netted out (they sit in bytes_in_use),
    but the budget is consumed as a TOTAL cap by the shared accounting
    in ``eligible`` — so they are added back, making the auto budget a
    total cap too.  Without this, a round-start refresh would charge
    every pinned pool twice (once inside bytes_in_use, once in
    pinned_bytes) and reject new pools that actually fit.  The static
    fallback budget is already a total cap, so ``pinned`` is NOT added
    there.

    ``stats`` injects a memory_stats dict for tests; by default the first
    local device is asked.  Backends that expose no memory statistics
    (CPU, some tunneled runtimes) fall back to the conservative static
    default so tests/parity behavior is unchanged off-accelerator."""
    from ..config import RESIDENT_SCORING_BYTES_DEFAULT

    if stats is None:
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
        except Exception:
            stats = {}
    limit = stats.get("bytes_limit")
    in_use = stats.get("bytes_in_use", 0)
    if not limit:
        budget = RESIDENT_SCORING_BYTES_DEFAULT
    else:
        budget = max(0, int(limit) - int(in_use) - int(reserve_bytes)) \
            + int(pinned)
    if jax.process_count() > 1:
        # Every process must resolve the SAME budget: the budget decides
        # resident-vs-streamed scoring, which are different collective
        # programs — per-process headroom (allocator state differs across
        # hosts) would deadlock the mesh at the first boundary pool.  Take
        # the fleet minimum so a pinned pool fits everywhere.  All
        # processes call this at the same points (trainer init, round
        # start), so the collective is in lockstep.
        from jax.experimental import multihost_utils
        budget = int(np.min(multihost_utils.process_allgather(
            np.asarray(budget, np.int64))))
    return budget


def resolve_budget(spec: Optional[int],
                   stats: Optional[Dict[str, int]] = None,
                   cache: Optional[Dict] = None) -> int:
    """TrainConfig.resident_scoring_bytes -> concrete byte budget:
    None = auto-size from live HBM headroom (pool residency is the
    DEFAULT behavior, not an override); an explicit integer — including
    0 to disable — is taken as-is.  ``cache``: the caller's resident
    cache, so a live-headroom auto budget stays a TOTAL cap alongside
    the shared accounting (see auto_budget's ``pinned``)."""
    if spec is None:
        budget = auto_budget(stats=stats, pinned=pinned_bytes(cache))
        get_logger().debug(
            f"resident pool budget auto-sized to {budget / 1e9:.1f} GB")
        return budget
    return int(spec)


def pinned_bytes(cache: Optional[Dict]) -> int:
    """Total bytes of every image array currently pinned in ``cache``
    (per-replica logical bytes — replication is per-chip, and the budget
    is a per-chip HBM figure)."""
    if not cache:
        return 0
    return sum(int(entry[1].nbytes)
               for entry in cache.get("images", {}).values())


def eligible(dataset: Any, max_bytes: int,
             cache: Optional[Dict] = None) -> bool:
    """In-memory (ArrayDataset-style) and within the byte budget.

    With a ``cache``, the budget is shared across every pinned array:
    a new pool is admitted only if it fits ALONGSIDE what is already
    resident, and an already-pinned pool is ALWAYS eligible — checked
    before the budget guard, so a pool pinned before the budget shrank
    (even to 0) keeps its fast path: its bytes sit in HBM either way,
    and streaming would pay twice (the rule previously restated as
    ``or cached(...)`` at every call site — this is the one spelling).
    Without a cache (direct callers), the old single-array check
    applies."""
    if cache is not None and cached(cache, dataset):
        return True
    images = getattr(dataset, "images", None)
    if not (max_bytes > 0 and isinstance(images, np.ndarray)):
        return False
    return (pinned_bytes(cache) + images[: len(dataset)].nbytes
            <= max_bytes)


def cached(cache: Optional[Dict], dataset: Any) -> bool:
    """True when ``dataset``'s images are ALREADY uploaded in this cache.
    A pool that is resident stays usable even after an auto-budget
    refresh shrinks the budget below its size — its bytes are part of
    the in-use figure the refresh measured, so dropping to the host path
    would pay streaming cost while the HBM stays pinned anyway."""
    if not cache:
        return False
    images = getattr(dataset, "images", None)
    if not isinstance(images, np.ndarray):
        return False
    return (id(images), len(dataset)) in cache.get("images", {})


def pool_arrays(cache: Dict, dataset: Any, mesh) -> Tuple[Any, Any]:
    """(images_dev, labels_dev) for the dataset, uploaded once per
    (underlying array, length) — views sharing storage share the upload.
    replicate() device_puts EXPLICITLY (transfer-guard friendly).  Every
    access refreshes the entry's position in the LRU eviction order."""
    images = cache.setdefault("images", {})
    n = len(dataset)
    key = (id(dataset.images), n)
    if key not in images:
        images[key] = (
            dataset,
            mesh_lib.replicate(
                np.ascontiguousarray(dataset.images[:n]), mesh),
            mesh_lib.replicate(
                dataset.targets[:n].astype(np.int32), mesh))
    lru = cache.setdefault("lru", [])
    if key in lru:
        lru.remove(key)
    lru.append(key)
    return images[key][1], images[key][2]


def enforce_budget(cache: Optional[Dict], max_bytes: int) -> list:
    """Demote pinned pools, least-recently-used first, until the cache
    fits ``max_bytes`` — the clean-shrink path for an EXPLICIT budget
    that got smaller mid-run (the AUTO budget never demotes: an
    already-pinned pool's bytes are part of the headroom it measures,
    see ``cached``).  Dropping the entry releases the device buffers;
    consumers notice via ``cached()`` turning False and fall back to
    their host paths at the next call — no shape change, no recompile,
    because the host paths' batch shapes were never a function of
    residency.  Returns the demoted keys."""
    if not cache:
        return []
    images = cache.get("images", {})
    lru = cache.get("lru", [])
    demoted = []
    while images and pinned_bytes(cache) > max(0, int(max_bytes)):
        key = next((k for k in lru if k in images), next(iter(images)))
        images.pop(key)
        if key in lru:
            lru.remove(key)
        demoted.append(key)
    if demoted:
        get_logger().info(
            f"resident pool budget shrank to {max_bytes / 1e9:.2f} GB: "
            f"demoted {len(demoted)} pinned pool(s); affected consumers "
            "fall back to host-streamed paths")
    return demoted


def get_runner(cache: Dict, step_fn: Callable, mesh,
               with_labels: bool = False) -> Callable:
    """Jitted gather+step over a resident pool: rows are picked out on
    device and constrained to the batch sharding, so each batch costs one
    tiny [batch]-int32 transfer instead of the image rows."""
    steps = cache.setdefault("steps", {})
    key = (id(step_fn), with_labels)
    if key not in steps:
        batch_sharding = mesh_lib.batch_sharding(mesh)

        if with_labels:

            @jax.jit
            def run(variables, images, labels, ids, mask):
                batch = {
                    "image": jax.lax.with_sharding_constraint(
                        images[ids], batch_sharding),
                    "label": labels[ids],
                    "mask": mask,
                }
                return step_fn(variables, batch)
        else:

            @jax.jit
            def run(variables, images, ids, mask):
                batch = {
                    "image": jax.lax.with_sharding_constraint(
                        images[ids], batch_sharding),
                    "mask": mask,
                }
                return step_fn(variables, batch)

        steps[key] = run
    return steps[key]
