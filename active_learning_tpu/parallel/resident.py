"""Device-resident pools: in-memory dataset rows uploaded once per
experiment and gathered ON DEVICE per batch.

One cache serves every consumer — acquisition scoring
(strategies/scoring.py), evaluation, AND the trainer's resident-gather
train feed (train/trainer.py) — so a pool whose views share storage
(ArrayDataset.with_view) is uploaded exactly once and that single pinned
array feeds scoring, validation, and training.  The byte budget is
accounted across the WHOLE cache: ``eligible`` admits a new array only
when it fits alongside everything already pinned, so "one pinned pool
serves both scoring and training" is also one set of bytes in the
budget, never two.  Entries retain their dataset object: keys include
id()s, and without the reference a recycled id could silently alias
another pool's images.

Residency layout (DESIGN.md §2b): a pool pins either REPLICATED (one
full copy per chip — the pre-sharding behavior, and the only option on
multi-process meshes today) or ROW-SHARDED (``NamedSharding(mesh,
P('data', ...))`` over pool rows: each chip holds ``rows/num_devices``,
so the budget question changes from "does the pool fit on a chip" to
"does rows/num_devices fit").  ``resolve_sharding`` owns the auto rule
(row whenever the single-process mesh has >1 device); ``pinned_bytes``
accounts PER-DEVICE bytes either way, so one budget figure stays a
per-chip HBM figure across both layouts.  Batches are fetched from a
row-sharded pool by ``sharded_pool_gather``: each shard contributes its
owned rows (masked, then psum'd from the owner — batch-sized traffic,
never pool-sized) and the result lands batch-sharded, exactly where the
replicated path's sharding constraint put it — so consumers are
bit-identical across layouts.

Layout of a cache dict:
  cache["images"][(id(images), n)] = (dataset, images_dev, labels_dev)
  cache["steps"][(id(step_fn), with_labels, sharded)] = jitted runner
  cache["lru"] = [key, ...]  # least-recently-used first (eviction order)

Virtual-CPU-mesh caveat: the N replicas' on-device gathers execute
serially on one core there, so resident paths can measure slower on the
test mesh; on real chips the replicas are parallel and the gather
replaces a host->device transfer.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_lib
from .. import faults
from ..utils.logging import get_logger

# Device transfer is the classic transient-failure surface (HBM pressure
# beside a live run, a tunneled backend hiccup): the once-per-experiment
# pool upload retries under the ONE RetryPolicy instead of the ad-hoc
# guards that used to live at each transfer site.  OOM is NOT retried —
# re-uploading into the same full HBM fails the same way; the driver's
# degradation ladder owns that case.
_UPLOAD_RETRY = faults.RetryPolicy(site="h2d_upload",
                                   classify=faults.classify_exception,
                                   max_attempts=3)

# The resident cache has CONCURRENT consumers since the pipelined round
# (experiment/pipeline.py): the speculative scorer thread and the
# trainer's per-epoch validation can both resolve the same pool entry
# while training runs.  One process-wide lock around cache mutation
# (first upload, runner build, LRU touch, budget demotion) AND the
# accounting reads that iterate the entry dict (pinned_bytes, cached)
# keeps "upload once per experiment" true under that concurrency and
# keeps a reader from hitting "dict changed size during iteration"
# while the other thread inserts.  Reads of an existing entry still pay
# only the lock handshake.  Reentrant: enforce_budget calls
# pinned_bytes under the lock.
_CACHE_LOCK = threading.RLock()

# Lock discipline, statically enforced (scripts/al_lint.py
# lock-discipline): the cache's shared maps may only be touched under
# _CACHE_LOCK — the speculative scorer, the trainer's validation, and
# the LRU/demotion paths all race on them otherwise.  ``update_warm``
# is the incremental updater's warmed-(layout, shape) marker set.
_GUARDED_BY = {"images": "_CACHE_LOCK",
               "steps": "_CACHE_LOCK",
               "lru": "_CACHE_LOCK",
               "update_warm": "_CACHE_LOCK"}

# Registered step-builders (al_lint recompile-hazard): the jitted
# gather+step runners are built once per (step_fn, labels, layout) and
# cached in the shared resident pool; the incremental row updater is
# built once per (layout, window width) the same way, and its warm-up
# dummy is a once-per-(layout, shape) device-side zeros.
_STEP_BUILDERS = ("get_runner", "_update_runner", "_dummy_like")

# HBM held back from the auto-sized resident budget: training activations,
# XLA workspace, and the model/optimizer trees all coexist with a pinned
# pool.  4 GB covers the ResNet-50 224px train step at 256 rows/chip
# (bf16 activations ~26 KB/row/layer-group, measured envelope well under
# 3 GB) with headroom for compile-time scratch.
AUTO_RESERVE_BYTES = 4 << 30


def auto_budget(reserve_bytes: int = AUTO_RESERVE_BYTES,
                stats: Optional[Dict[str, int]] = None,
                pinned: int = 0) -> int:
    """Size the device-resident pool budget from LIVE HBM headroom:
    (bytes_limit − bytes_in_use) − reserve, floored at 0.

    ``pinned``: bytes ALREADY pinned in the caller's resident cache.
    Live headroom has those bytes netted out (they sit in bytes_in_use),
    but the budget is consumed as a TOTAL cap by the shared accounting
    in ``eligible`` — so they are added back, making the auto budget a
    total cap too.  Without this, a round-start refresh would charge
    every pinned pool twice (once inside bytes_in_use, once in
    pinned_bytes) and reject new pools that actually fit.  The static
    fallback budget is already a total cap, so ``pinned`` is NOT added
    there.

    ``stats`` injects a memory_stats dict for tests; by default the first
    local device is asked.  Backends that expose no memory statistics
    (CPU, some tunneled runtimes) fall back to the conservative static
    default so tests/parity behavior is unchanged off-accelerator."""
    from ..config import RESIDENT_SCORING_BYTES_DEFAULT

    if stats is None:
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
        except Exception:
            stats = {}
    limit = stats.get("bytes_limit")
    in_use = stats.get("bytes_in_use", 0)
    if not limit:
        budget = RESIDENT_SCORING_BYTES_DEFAULT
    else:
        budget = max(0, int(limit) - int(in_use) - int(reserve_bytes)) \
            + int(pinned)
    if jax.process_count() > 1:
        # Every process must resolve the SAME budget: the budget decides
        # resident-vs-streamed scoring, which are different collective
        # programs — per-process headroom (allocator state differs across
        # hosts) would deadlock the mesh at the first boundary pool.  Take
        # the fleet minimum so a pinned pool fits everywhere.  All
        # processes call this at the same points (trainer init, round
        # start), so the collective is in lockstep.
        from jax.experimental import multihost_utils
        budget = int(np.min(multihost_utils.process_allgather(
            np.asarray(budget, np.int64))))
    return budget


def resolve_budget(spec: Optional[int],
                   stats: Optional[Dict[str, int]] = None,
                   cache: Optional[Dict] = None) -> int:
    """TrainConfig.resident_scoring_bytes -> concrete byte budget:
    None = auto-size from live HBM headroom (pool residency is the
    DEFAULT behavior, not an override); an explicit integer — including
    0 to disable — is taken as-is.  ``cache``: the caller's resident
    cache, so a live-headroom auto budget stays a TOTAL cap alongside
    the shared accounting (see auto_budget's ``pinned``)."""
    if spec is None:
        budget = auto_budget(stats=stats, pinned=pinned_bytes(cache))
        get_logger().debug(
            f"resident pool budget auto-sized to {budget / 1e9:.1f} GB")
        return budget
    return int(spec)


def resolve_sharding(spec: Optional[str], mesh) -> str:
    """TrainConfig.pool_sharding -> the concrete resident layout,
    "replicated" or "row".  "auto" (or None): row whenever the mesh has
    more than one device — per-chip residency then scales 1/ndev with
    chip count for free, and on MULTI-PROCESS meshes (the pod tier,
    DESIGN.md §15) each host additionally assembles only its own shard
    of the upload (mesh_lib.shard_rows' per-process arm), so the pool
    never lands whole on any one host either.  Only single-device
    meshes stay replicated (sharding over one device is replication
    with extra steps)."""
    if spec in (None, "auto"):
        spec = "row"
    if spec not in ("replicated", "row"):
        raise ValueError(
            f"pool_sharding={spec!r} is not one of 'auto'/'replicated'/"
            "'row'")
    if spec == "row" and (mesh is None or mesh.devices.size <= 1):
        return "replicated"
    return spec


def _per_device_bytes(array: Any) -> int:
    """HBM bytes one device holds for ``array``: the largest addressable
    shard (replicated arrays shard as full copies, row-sharded ones as
    rows/ndev) — so the budget stays a per-chip figure across layouts."""
    shards = getattr(array, "addressable_shards", None)
    if shards:
        return max(int(s.data.nbytes) for s in shards)
    return int(array.nbytes)


def pinned_bytes(cache: Optional[Dict]) -> int:
    """Total PER-DEVICE bytes of every image array currently pinned in
    ``cache`` (replicated entries cost their full size per chip,
    row-sharded entries rows/ndev — the budget is a per-chip HBM
    figure either way)."""
    if not cache:
        return 0
    with _CACHE_LOCK:
        return sum(_per_device_bytes(entry[1])
                   for entry in cache.get("images", {}).values())


def eligible(dataset: Any, max_bytes: int,
             cache: Optional[Dict] = None,
             shard_ways: int = 1) -> bool:
    """In-memory (ArrayDataset-style) and within the byte budget.

    With a ``cache``, the budget is shared across every pinned array:
    a new pool is admitted only if it fits ALONGSIDE what is already
    resident, and an already-pinned pool is ALWAYS eligible — checked
    before the budget guard, so a pool pinned before the budget shrank
    (even to 0) keeps its fast path: its bytes sit in HBM either way,
    and streaming would pay twice (the rule previously restated as
    ``or cached(...)`` at every call site — this is the one spelling).
    Without a cache (direct callers), the old single-array check
    applies.

    ``shard_ways``: how many devices a prospective upload would be
    row-sharded over (1 = replicated).  Under row sharding a chip pins
    only ceil(rows/ways) rows, so the budget admits pools ~ways times
    larger — the scale-out the sharded pool exists for."""
    if cache is not None and cached(cache, dataset):
        return True
    images = getattr(dataset, "images", None)
    if not (max_bytes > 0 and isinstance(images, np.ndarray)):
        return False
    n = len(dataset)
    ways = max(1, int(shard_ways))
    row_bytes = int(np.prod(images.shape[1:])) * images.itemsize
    need = -(-n // ways) * row_bytes  # ceil: covers the shard pad rows
    return pinned_bytes(cache) + need <= max_bytes


def cached(cache: Optional[Dict], dataset: Any) -> bool:
    """True when ``dataset``'s images are ALREADY uploaded in this cache.
    A pool that is resident stays usable even after an auto-budget
    refresh shrinks the budget below its size — its bytes are part of
    the in-use figure the refresh measured, so dropping to the host path
    would pay streaming cost while the HBM stays pinned anyway."""
    if not cache:
        return False
    images = getattr(dataset, "images", None)
    if not isinstance(images, np.ndarray):
        return False
    # Under the cache lock like every other reader: the speculative
    # scorer resolves entries concurrently with the trainer's uploads,
    # and this membership probe was the one access left bare (found by
    # the lock-discipline checker; the GIL made it merely racy-looking
    # on CPython, but the discipline is the contract).
    with _CACHE_LOCK:
        return (id(images), len(dataset)) in cache.get("images", {})


def pool_arrays(cache: Dict, dataset: Any, mesh,
                sharding: str = "replicated") -> Tuple[Any, Any]:
    """(images_dev, labels_dev) for the dataset, uploaded once per
    (underlying array, length) — views sharing storage share the upload.
    ``sharding`` "row": rows split over the mesh's data axis
    (mesh_lib.shard_rows — zero-padded to divide evenly; the full array
    never lands on any single device), "replicated": one copy per chip.
    The FIRST upload fixes an entry's layout (the mode is a per-
    experiment deployment choice, resolved once by resolve_sharding);
    consumers detect it off the array itself (mesh_lib.is_row_sharded).
    replicate()/shard_rows device_put EXPLICITLY (transfer-guard
    friendly).  Every access refreshes the entry's position in the LRU
    eviction order."""
    with _CACHE_LOCK:
        images = cache.setdefault("images", {})
        n = len(dataset)
        key = (id(dataset.images), n)
        if key not in images:

            def _upload():
                faults.site("h2d_upload")
                if sharding == "row" and mesh.devices.size > 1:
                    # No ascontiguousarray here: shard_rows slices per
                    # shard (and makes each block contiguous itself), so
                    # the one big host copy the replicated path pays is
                    # exactly what the row path avoids.
                    return (
                        dataset,
                        mesh_lib.shard_rows(dataset.images[:n], mesh),
                        mesh_lib.shard_rows(
                            dataset.targets[:n].astype(np.int32), mesh))
                return (
                    dataset,
                    mesh_lib.replicate(
                        np.ascontiguousarray(dataset.images[:n]), mesh),
                    mesh_lib.replicate(
                        dataset.targets[:n].astype(np.int32), mesh))

            images[key] = _UPLOAD_RETRY.call(_upload)
        lru = cache.setdefault("lru", [])
        if key in lru:
            lru.remove(key)
        lru.append(key)
        return images[key][1], images[key][2]


def sharded_pool_gather(images, ids, mesh, labels=None):
    """Rows of a ROW-SHARDED pool for a replicated [batch] index vector,
    returned batch-sharded — the sharded pool's one batch-fetch
    primitive, shared by the scoring/eval runners (get_runner) and the
    trainer's resident-gather feed.  Traceable: shard_map composes under
    jit and inside lax.scan, so callers embed it in their own jitted
    steps.

    Mechanics (all inside shard_map over the data axis): every shard
    masks the batch ids it owns, gathers those rows locally, and a psum
    assembles the full batch from the owners (non-owners contribute
    exact zeros — the sum is the owner's bytes, bit for bit, uint8
    included).  Traffic is batch-sized, never pool-sized; each shard
    then keeps only ITS slice of the batch, so the output lands exactly
    where the replicated path's ``with_sharding_constraint(images[ids],
    batch_sharding)`` put it and every downstream consumer partitions
    identically — which is why batches are bit-identical across pool
    layouts (tests/test_pool_sharding.py).

    The global batch must divide the mesh (Trainer.padded_batch_size
    guarantees it for every caller)."""
    axis = mesh_lib.DATA_AXIS
    ndev = mesh.devices.size

    def local_gather(pool, idv):
        full = mesh_lib.owner_rows(pool, idv, axis)
        i = jax.lax.axis_index(axis)
        b_local = idv.shape[0] // ndev
        return jax.lax.dynamic_slice_in_dim(full, i * b_local, b_local, 0)

    img_spec = P(axis, *([None] * (images.ndim - 1)))
    if labels is None:
        return shard_map(local_gather, mesh=mesh,
                         in_specs=(img_spec, P()), out_specs=img_spec,
                         check_rep=False)(images, ids)
    return shard_map(
        lambda im, lb, idv: (local_gather(im, idv), local_gather(lb, idv)),
        mesh=mesh, in_specs=(img_spec, P(axis), P()),
        out_specs=(img_spec, P(axis)), check_rep=False)(images, labels, ids)


# The incremental row update's FIXED window width (rows): every drain,
# whatever its size, applies as a sequence of exactly-this-wide blocks
# (the tail block slides back over already-current rows, an identity
# rewrite), so ONE jitted updater per (layout, entry shape) covers
# every drain — a 1000-row append can never compile a fresh width
# inside a warm round.
UPDATE_BLOCK_FLOOR = 64


def _update_runner(cache: Dict, mesh, sharded: bool, width: int
                   ) -> Callable:
    """Jitted in-place row updater for a pinned pool entry, one per
    (layout, window width), cached beside the gather runners: a
    ``[width, ...]`` host block lands at row ``lo`` of the resident
    array via ``dynamic_update_slice`` — the ONLY image bytes that
    cross the host->device boundary on an in-extent streaming drain.
    Replicated entries donate the old buffer (XLA updates in place);
    row-sharded entries scatter each block row to its owning shard
    (local index math + mode="drop", no collectives) — donation is
    skipped there, matching the sharded k-center jits (XLA:CPU rejects
    donating sharded buffers with a per-call warning)."""
    key = ("update_rows", bool(sharded), int(width))
    with _CACHE_LOCK:
        steps = cache.setdefault("steps", {})
        if key in steps:
            return steps[key]
    axis = mesh_lib.DATA_AXIS

    if sharded:

        @jax.jit
        def run(images, block, lo):
            def body(img, blk, lo_):
                rows = img.shape[0]
                off = (jax.lax.axis_index(axis) * rows).astype(jnp.int32)
                gidx = lo_.astype(jnp.int32) + jnp.arange(
                    blk.shape[0], dtype=jnp.int32)
                # Off-shard rows park PAST the shard (rows) so
                # mode="drop" discards them — the _owned_or_oob rule
                # (a bare gidx - off would wrap negative indices).
                loc = jnp.where((gidx >= off) & (gidx < off + rows),
                                gidx - off, rows)
                return img.at[loc].set(blk, mode="drop")

            spec = P(axis, *([None] * (images.ndim - 1)))
            return shard_map(body, mesh=mesh, in_specs=(spec, P(), P()),
                             out_specs=spec,
                             check_rep=False)(images, block, lo)
    else:

        @functools.partial(
            jax.jit, donate_argnums=(0,),
            out_shardings=mesh_lib.replicated_sharding(mesh))
        def run(images, block, lo):
            return jax.lax.dynamic_update_slice(
                images, block, (lo,) + (0,) * (images.ndim - 1))

    with _CACHE_LOCK:
        return steps.setdefault(key, run)


def update_rows(cache: Optional[Dict], dataset: Any, mesh,
                row_lo: int, row_hi: int) -> bool:
    """Incrementally refresh a PINNED pool entry after a streaming
    drain that appended rows (or attached labels) WITHOUT growing the
    extent: rows ``[row_lo, row_hi)`` ride h2d as a sequence of
    fixed-width blocks ``dynamic_update_slice``'d into the resident
    array IN PLACE (the tail block slides back over already-current
    rows — an identity rewrite — so every dispatch has the ONE
    prewarmed shape); the pinned extent is never re-uploaded.  Labels
    re-upload whole (a [capacity]-int32 device_put: tiny, never a
    compile) so label-only records are covered by the same call, and
    they upload BEFORE the first donating image dispatch — a transient
    label-upload failure leaves the entry untouched and valid.
    Returns False when the entry is not pinned or smaller than one
    window — the caller falls back to ``release`` + re-upload (the
    extent-boundary path).  A failure INSIDE the donating image
    update drops the entry before re-raising: the old buffer may
    already be consumed, and a cache entry pointing at a deleted
    array would poison every retry (the next access re-uploads
    instead).

    Caller contract: a drain point with no in-flight consumers of the
    entry's arrays (the stream service's single mutation point) — the
    replicated form DONATES the old buffer."""
    images = getattr(dataset, "images", None)
    if not isinstance(images, np.ndarray):
        return False
    n = len(dataset)
    key = (id(images), n)
    with _CACHE_LOCK:
        entry = cache.get("images", {}).get(key) if cache else None
    if entry is None:
        return False
    _, images_dev, _ = entry
    sharded = mesh_lib.is_row_sharded(images_dev)
    width = int(row_hi) - int(row_lo)
    block_rows = UPDATE_BLOCK_FLOOR
    if width > 0 and block_rows > n:
        return False
    # Labels FIRST, under the ONE upload RetryPolicy: no donation is
    # involved, so a transient H2D failure retries (and a final failure
    # propagates) with the entry still intact and valid.
    def _labels():
        if sharded:
            return mesh_lib.shard_rows(
                dataset.targets[:n].astype(np.int32), mesh)
        return mesh_lib.replicate(
            dataset.targets[:n].astype(np.int32), mesh)

    new_labels = _UPLOAD_RETRY.call(_labels)
    new_images = images_dev
    if width > 0:
        run = _update_runner(cache, mesh, sharded, block_rows)
        try:
            for lo0 in range(int(row_lo), int(row_hi), block_rows):
                lo = min(lo0, n - block_rows)
                block = np.ascontiguousarray(images[lo:lo + block_rows])
                new_images = run(new_images, block, jnp.int32(lo))
        except Exception:
            # The old buffer may be donated-and-gone: drop the entry so
            # the next access re-uploads cleanly instead of dispatching
            # against a deleted array forever.
            release(cache, dataset)
            raise
    with _CACHE_LOCK:
        images_map = cache.get("images", {})
        if key not in images_map:
            return False
        images_map[key] = (dataset, new_images, new_labels)
        lru = cache.setdefault("lru", [])
        if key in lru:
            lru.remove(key)
        lru.append(key)
        cache.setdefault("update_warm",
                         set()).add((sharded, images_dev.shape))
    return True


def prewarm_update(cache: Optional[Dict], dataset: Any, mesh) -> bool:
    """Build + warm the incremental updater for ``dataset``'s pinned
    entry by dispatching it once against a THROWAWAY zeros array of the
    entry's exact shape/layout — so the first real in-extent drain
    dispatches a warm executable instead of paying a compile inside a
    warm round (the jit-delta-0 contract, tests/test_compile_reuse.py).
    The stream service calls this right after each round, landing the
    compile in that round's (already-taxed) window.  Deliberately
    touches NEITHER the entry nor its buffers: the pipelined round's
    speculative scorer may still hold the live array, and a donating
    identity update here would delete it out from under that thread
    (update_rows' no-in-flight-consumers contract is the DRAIN point's
    to establish, not this warm-up's).  A TRUE no-op once the (layout,
    entry shape) pair is warmed — the marker re-arms after extent
    growth (same jit, new shape trace) and skips everything (no h2d,
    no dispatch) otherwise.  False when the entry is not pinned or too
    small to ever use the updater."""
    images = getattr(dataset, "images", None)
    if cache is None or not isinstance(images, np.ndarray) \
            or len(dataset) < UPDATE_BLOCK_FLOOR:
        return False
    key = (id(images), len(dataset))
    with _CACHE_LOCK:
        entry = cache.get("images", {}).get(key)
        if entry is None:
            return False
        images_dev = entry[1]
        sharded = mesh_lib.is_row_sharded(images_dev)
        marker = (sharded, images_dev.shape)
        if marker in cache.get("update_warm", set()):
            return True
    run = _update_runner(cache, mesh, sharded, UPDATE_BLOCK_FLOOR)
    dummy = _dummy_like(images_dev, mesh, sharded)
    block = np.zeros((UPDATE_BLOCK_FLOOR, *images_dev.shape[1:]),
                     images_dev.dtype)
    run(dummy, block, jnp.int32(0))  # warmed; the dummy is garbage now
    with _CACHE_LOCK:
        cache.setdefault("update_warm", set()).add(marker)
    return True


def _dummy_like(images_dev, mesh, sharded: bool):
    """Device-side zeros in a pinned entry's exact shape/dtype/layout —
    the warm-up stand-in prewarm_update dispatches the updater against.
    Built ON DEVICE (``jnp.zeros`` under an out_shardings-pinned jit):
    a host-side zeros of a multi-GB pool would transiently double the
    host allocation AND pay pool-scale H2D per device just to warm an
    executable.  Compiles once per (layout, shape) — exactly the
    cadence prewarm runs it (the marker gates re-entry), inside the
    already-taxed round window."""
    sharding = (mesh_lib.row_sharding(mesh) if sharded
                else mesh_lib.replicated_sharding(mesh))
    return jax.jit(
        functools.partial(jnp.zeros, images_dev.shape, images_dev.dtype),
        out_shardings=sharding)()


def pin_hot(cache: Optional[Dict], tag: str,
            images_dev: Any, labels_dev: Any) -> bool:
    """Register an ALREADY-UPLOADED hot row block under the shared
    budget accounting — the disk tier's HBM leg (DESIGN.md §16): a
    demand-paged pool never pins whole (its ``.images`` raises by
    contract), but the trainer's hot labeled-subset copy is HBM like
    any pinned pool and must show up in ``pinned_bytes`` so the ONE
    per-chip budget figure covers all three tiers.  Keyed by ``tag``
    (one slot per trainer): re-pinning the same tag replaces the entry
    — the previous round's hot copy is released, never double-counted.
    The entry stores no dataset (a paged pool has no id(images) to
    key by); ``pinned_bytes`` and ``enforce_budget`` never inspect
    keys, so the synthetic entry demotes LRU-first like any other —
    a demotion only drops the cache's reference (the running fit holds
    its own), so the budget squeeze lands at the NEXT fit's resolve."""
    if cache is None:
        return False
    key = ("hot", tag)
    with _CACHE_LOCK:
        cache.setdefault("images", {})[key] = (None, images_dev,
                                               labels_dev)
        lru = cache.setdefault("lru", [])
        if key in lru:
            lru.remove(key)
        lru.append(key)
    return True


def unpin_hot(cache: Optional[Dict], tag: str) -> bool:
    """Drop a ``pin_hot`` entry (if present) — the disk tier's release
    hook when a trainer's hot copy is abandoned rather than replaced."""
    if not cache:
        return False
    key = ("hot", tag)
    with _CACHE_LOCK:
        entry = cache.get("images", {}).pop(key, None)
        lru = cache.get("lru", [])
        if key in lru:
            lru.remove(key)
    return entry is not None


def release(cache: Optional[Dict], dataset: Any) -> bool:
    """Drop ``dataset``'s pinned entry (if any) so the NEXT access
    re-uploads — the streaming subsystem's invalidation hook: an ingest
    drain appends real rows into extent slots that were zero padding
    when the pool was pinned, so the device copy is stale row-wise even
    though its shape (the extent capacity) is unchanged.  Dropping the
    entry costs one re-upload at the old shape; it never costs a
    compile, because the gather runners are keyed on (step_fn, layout),
    not on the array.  Returns True when an entry was actually
    dropped."""
    if not cache:
        return False
    images = getattr(dataset, "images", None)
    if not isinstance(images, np.ndarray):
        return False
    key = (id(images), len(dataset))
    with _CACHE_LOCK:
        entry = cache.get("images", {}).pop(key, None)
        lru = cache.get("lru", [])
        if key in lru:
            lru.remove(key)
    return entry is not None


def enforce_budget(cache: Optional[Dict], max_bytes: int) -> list:
    """Demote pinned pools, least-recently-used first, until the cache
    fits ``max_bytes`` — the clean-shrink path for an EXPLICIT budget
    that got smaller mid-run (the AUTO budget never demotes: an
    already-pinned pool's bytes are part of the headroom it measures,
    see ``cached``).  Dropping the entry releases the device buffers;
    consumers notice via ``cached()`` turning False and fall back to
    their host paths at the next call — no shape change, no recompile,
    because the host paths' batch shapes were never a function of
    residency.  Returns the demoted keys."""
    if not cache:
        return []
    demoted = []
    with _CACHE_LOCK:
        images = cache.get("images", {})
        lru = cache.get("lru", [])
        while images and pinned_bytes(cache) > max(0, int(max_bytes)):
            key = next((k for k in lru if k in images), next(iter(images)))
            images.pop(key)
            if key in lru:
                lru.remove(key)
            demoted.append(key)
    if demoted:
        get_logger().info(
            f"resident pool budget shrank to {max_bytes / 1e9:.2f} GB: "
            f"demoted {len(demoted)} pinned pool(s); affected consumers "
            "fall back to host-streamed paths")
    return demoted


def get_runner(cache: Dict, step_fn: Callable, mesh,
               with_labels: bool = False, sharded: bool = False) -> Callable:
    """Jitted gather+step over a resident pool: rows are picked out on
    device and constrained to the batch sharding, so each batch costs one
    tiny [batch]-int32 transfer instead of the image rows.  ``sharded``
    (caller reads it off the entry via mesh_lib.is_row_sharded): the
    gather goes through sharded_pool_gather — shard-local row pick +
    owner psum instead of a full-array index — landing the batch in the
    SAME batch sharding, so the step partitions identically and scores
    are bit-identical across pool layouts."""
    key = (id(step_fn), with_labels, bool(sharded))
    with _CACHE_LOCK:
        steps = cache.setdefault("steps", {})
        if key in steps:
            return steps[key]
    batch_sharding = mesh_lib.batch_sharding(mesh)

    if with_labels:

        @jax.jit
        def run(variables, images, labels, ids, mask):
            if sharded:
                img, lab = sharded_pool_gather(images, ids, mesh,
                                               labels=labels)
            else:
                img = jax.lax.with_sharding_constraint(
                    images[ids], batch_sharding)
                lab = labels[ids]
            batch = {"image": img, "label": lab, "mask": mask}
            return step_fn(variables, batch)
    else:

        @jax.jit
        def run(variables, images, ids, mask):
            if sharded:
                img = sharded_pool_gather(images, ids, mesh)
            else:
                img = jax.lax.with_sharding_constraint(
                    images[ids], batch_sharding)
            batch = {"image": img, "mask": mask}
            return step_fn(variables, batch)

    # setdefault under the lock: if another thread built the same runner
    # meanwhile, ONE wins and both callers share it — two live runner
    # objects for one (step_fn, layout) would each compile separately.
    with _CACHE_LOCK:
        return steps.setdefault(key, run)
