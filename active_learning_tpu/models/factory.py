"""Model factory: dataset/model-name -> SSLClassifier.

Mirrors src/utils/get_networks.py (MODEL_ARGS/DATA_ARGS tables and
``get_networks(dataset, model)``), with the CIFAR stem driven explicitly by
the dataset's class count like the reference's ``num_classes == 10`` trigger
(resnet_simclr.py:17-18).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from ..registry import MODELS
from .resnet import SSLClassifier, resnet18, resnet50

MODELS.register("SSLResNet18", resnet18)
MODELS.register("SSLResNet50", resnet50)

# Compute-precision names accepted by configs/CLI.  "auto" resolves by the
# live backend: the TPU MXU is bf16-native, everything else gets float32.
_DTYPE_NAMES = {
    "float32": jnp.float32, "f32": jnp.float32, "fp32": jnp.float32,
    "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
}


def resolve_dtype(spec: Any) -> Any:
    """Resolve a config dtype spec (name string, jnp dtype, or "auto") to
    the jnp compute dtype.  Parameters and BN statistics stay float32
    regardless — this only selects the conv/matmul precision
    (models/resnet.py)."""
    if spec is None or spec == "auto":
        import jax
        return (jnp.bfloat16 if jax.default_backend() == "tpu"
                else jnp.float32)
    if isinstance(spec, str):
        try:
            return _DTYPE_NAMES[spec.lower()]
        except KeyError:
            raise ValueError(
                f"Unknown dtype {spec!r}; expected one of "
                f"{sorted(_DTYPE_NAMES)} or 'auto'")
    return spec


def resolve_bn_stats_dtype(spec: Any, compute_dtype: Any) -> Any:
    """BN-statistics read precision: "auto" follows the COMPUTE dtype —
    bf16 models get the fused bf16-read/f32-accumulate statistics path
    (models/resnet.FusedBatchNorm), f32 models keep flax's BatchNorm so
    CPU/parity numerics are untouched.  Accumulation and the stored
    running statistics are float32 in every mode."""
    if spec is None or spec == "auto":
        return jnp.bfloat16 if compute_dtype == jnp.bfloat16 else None
    resolved = resolve_dtype(spec)
    return jnp.bfloat16 if resolved == jnp.bfloat16 else None

# Dataset -> class count (get_networks.py:3-6).
DATASET_NUM_CLASSES = {
    "cifar10": 10,
    "imbalanced_cifar10": 10,
    "imagenet": 1000,
    "imbalanced_imagenet": 1000,
    "synthetic": 10,
}


def get_network(
    dataset: str,
    model_name: str,
    freeze_feature: bool = False,
    num_classes: Optional[int] = None,
    dtype: Any = "auto",
    stem: str = "default",
    bn_stats_dtype: Any = "auto",
) -> SSLClassifier:
    if num_classes is None:
        try:
            num_classes = DATASET_NUM_CLASSES[dataset]
        except KeyError:
            raise KeyError(
                f"Unknown dataset '{dataset}'; pass num_classes explicitly")
    factory = MODELS.get(model_name)
    # The reference applies the SimCLR CIFAR stem whenever num_classes == 10
    # (resnet_simclr.py:17-18); keep that behavior.
    cifar_stem = num_classes == 10
    if stem in (None, "auto"):
        stem = "default"
    if stem == "s2d" and cifar_stem:
        # The CLI/arg-pool stem choice is global; CIFAR datasets keep their
        # SimCLR stem (there is no 7x7 conv to fold) rather than erroring.
        stem = "default"
    compute = resolve_dtype(dtype)
    return factory(num_classes=num_classes, cifar_stem=cifar_stem,
                   freeze_feature=freeze_feature, dtype=compute, stem=stem,
                   bn_stats_dtype=resolve_bn_stats_dtype(bn_stats_dtype,
                                                         compute))
