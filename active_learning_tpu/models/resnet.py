"""TPU-native (NHWC, Flax) ResNet-18/50 with the SimCLR CIFAR stem and a
split encoder / linear-classification head.

Capability parity with the reference's model stack:
  * torchvision resnet18/50 v1.5 topology wrapped by ``ResNetSimCLR``
    (src/models/resnet_simclr.py:6-41): encoder with ``fc`` removed plus a
    separate ``linear`` head.
  * SimCLR CIFAR stem modification — 3x3 stride-1 first conv, no max pool —
    applied when the dataset is CIFAR (src/models/resnet_hacks.py:31-35,
    triggered at resnet_simclr.py:17-18).
  * Three forward modes (resnet_simclr.py:29-41): plain logits,
    ``return_features`` (logits + final embedding), and head-only from an
    embedding (``specify_input_layer='finalembed'``) — here the explicit
    ``head`` method.
  * ``freeze_feature`` detaches the embedding (resnet_simclr.py:36-37) —
    here ``jax.lax.stop_gradient``.

Design notes (TPU-first, not a translation):
  * NHWC layout — XLA's native conv layout on TPU; convs tile directly onto
    the MXU.
  * ``dtype`` controls the compute precision (bfloat16 on TPU); parameters
    and batch-norm statistics stay float32.
  * Global-batch BatchNorm: under ``jit`` over a data-sharded mesh the batch
    reduction lowers to a cross-replica collective automatically, giving
    SyncBatchNorm semantics (reference: strategy.py:292) with no special
    wrapper.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any

# torch init_params semantics (src/models/utils.py:5-18): conv weights
# kaiming-normal fan_out, linear weights N(0, 1e-3), biases zero.  BatchNorm
# scale=1/bias=0 is the flax default.
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")
dense_kernel_init = nn.initializers.normal(stddev=1e-3)


class BasicBlock(nn.Module):
    """ResNet v1.5 basic block (two 3x3 convs) — resnet18/34."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = None
    norm: ModuleDef = None

    @nn.compact
    def __call__(self, x):
        # 3x3 convs use EXPLICIT (1, 1) padding, not "SAME": for stride-2
        # on even spatial sizes SAME pads (0, 1) while torch's padding=1
        # pads (1, 1) — a one-pixel window shift that silently breaks
        # converted torch checkpoints (tests/test_torch_parity.py).
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides,
                      padding=[(1, 1), (1, 1)])(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = self.norm(scale_init=nn.initializers.ones)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """ResNet v1.5 bottleneck (1x1 -> strided 3x3 -> 1x1 x4) — resnet50.

    The stride lives on the 3x3 conv, matching torchvision's v1.5 used by
    the reference (resnet_hacks.py docstring notes torchvision is v1.5).
    """

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = None
    norm: ModuleDef = None

    @nn.compact
    def __call__(self, x):
        # Same explicit-padding rule as BasicBlock for the strided 3x3.
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides,
                      padding=[(1, 1), (1, 1)])(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.ones)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class ResNetEncoder(nn.Module):
    """Backbone producing the pooled final embedding (fc removed, mirroring
    ``self.encoder.fc = nn.Identity()`` at resnet_simclr.py:21)."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_filters: int = 64
    cifar_stem: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype,
            kernel_init=conv_kernel_init)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=None)

        x = x.astype(self.dtype)
        if self.cifar_stem:
            # SimCLR CIFAR stem: 3x3 stride-1 conv, no max pool
            # (resnet_hacks.py:31-35).
            x = conv(self.num_filters, (3, 3), (1, 1), name="conv_stem")(x)
            x = norm(name="bn_stem")(x)
            x = nn.relu(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_stem")(x)
            x = norm(name="bn_stem")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2),
                            padding=[(1, 1), (1, 1)])

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm,
                    name=f"stage{i + 1}_block{j}")(x)

        # Global average pool -> final embedding, float32 for the head and
        # for downstream acquisition math (margins, pairwise distances).
        x = jnp.mean(x, axis=(1, 2))
        return x.astype(jnp.float32)


class SSLClassifier(nn.Module):
    """Encoder + separate linear head (resnet_simclr.py:20-22).

    Forward modes:
      * ``apply(vars, x)``                      -> logits
      * ``apply(vars, x, return_features=True)``-> (logits, embedding)
      * ``apply(vars, emb, method="head")``     -> logits from an embedding
        (the reference's ``specify_input_layer='finalembed'``).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    cifar_stem: bool = False
    freeze_feature: bool = False
    dtype: Any = jnp.float32

    def setup(self):
        self.encoder = ResNetEncoder(
            stage_sizes=self.stage_sizes, block_cls=self.block_cls,
            cifar_stem=self.cifar_stem, dtype=self.dtype, name="encoder")
        self.linear = nn.Dense(
            self.num_classes, kernel_init=dense_kernel_init,
            bias_init=nn.initializers.zeros, name="linear")

    def __call__(self, x, train: bool = True, return_features: bool = False):
        embedding = self.encoder(x, train=train)
        if self.freeze_feature:
            # Stop-gradient on the backbone output (resnet_simclr.py:36-37);
            # combined with eval-mode BN in the trainer this freezes the
            # feature extractor for linear evaluation.
            embedding = jax.lax.stop_gradient(embedding)
        logits = self.linear(embedding)
        if return_features:
            return logits, embedding
        return logits

    def head(self, embedding):
        return self.linear(embedding)

    @property
    def embed_dim(self) -> int:
        mult = 4 if self.block_cls is BottleneckBlock else 1
        return 64 * 2 ** (len(self.stage_sizes) - 1) * mult


def _make(stage_sizes, block_cls, num_classes, cifar_stem, freeze_feature,
          dtype):
    return SSLClassifier(
        stage_sizes=tuple(stage_sizes), block_cls=block_cls,
        num_classes=num_classes, cifar_stem=cifar_stem,
        freeze_feature=freeze_feature, dtype=dtype)


def resnet18(num_classes: int, cifar_stem: bool = False,
             freeze_feature: bool = False,
             dtype: Any = jnp.float32) -> SSLClassifier:
    return _make([2, 2, 2, 2], BasicBlock, num_classes, cifar_stem,
                 freeze_feature, dtype)


def resnet50(num_classes: int, cifar_stem: bool = False,
             freeze_feature: bool = False,
             dtype: Any = jnp.float32) -> SSLClassifier:
    return _make([3, 4, 6, 3], BottleneckBlock, num_classes, cifar_stem,
                 freeze_feature, dtype)
