"""TPU-native (NHWC, Flax) ResNet-18/50 with the SimCLR CIFAR stem and a
split encoder / linear-classification head.

Capability parity with the reference's model stack:
  * torchvision resnet18/50 v1.5 topology wrapped by ``ResNetSimCLR``
    (src/models/resnet_simclr.py:6-41): encoder with ``fc`` removed plus a
    separate ``linear`` head.
  * SimCLR CIFAR stem modification — 3x3 stride-1 first conv, no max pool —
    applied when the dataset is CIFAR (src/models/resnet_hacks.py:31-35,
    triggered at resnet_simclr.py:17-18).
  * Three forward modes (resnet_simclr.py:29-41): plain logits,
    ``return_features`` (logits + final embedding), and head-only from an
    embedding (``specify_input_layer='finalembed'``) — here the explicit
    ``head`` method.
  * ``freeze_feature`` detaches the embedding (resnet_simclr.py:36-37) —
    here ``jax.lax.stop_gradient``.

Design notes (TPU-first, not a translation):
  * NHWC layout — XLA's native conv layout on TPU; convs tile directly onto
    the MXU.
  * ``dtype`` controls the compute precision (bfloat16 on TPU); parameters
    and batch-norm statistics stay float32.
  * Global-batch BatchNorm: under ``jit`` over a data-sharded mesh the batch
    reduction lowers to a cross-replica collective automatically, giving
    SyncBatchNorm semantics (reference: strategy.py:292) with no special
    wrapper.
  * Space-to-depth stem (``stem="s2d"``): the 224px 7x7/s2 stem conv is an
    arithmetic-intensity sink on the 128x128 MXU (3 input channels leave
    126/128 of the contraction lanes idle).  Re-laying the input as
    112x112x12 (2x2 pixel blocks flattened into channels) and folding the
    7x7/s2 kernel into an exact 4x4/s1 kernel computes the identical
    convolution with 12 contraction channels — same multiplies, MXU-shaped
    (``s2d_stem_kernel`` is the exact weight transform; pinned bit-level by
    tests/test_s2d_stem.py).  The layout transform itself can run host-side
    (data/pipeline.space_to_depth — same byte count over PCIe) or on device
    (free reshape, fused); the encoder accepts either form.
  * Fused bf16 BN statistics (``bn_stats_dtype``): flax's BatchNorm promotes
    the FULL activation tensor to float32 before its mean/var reductions —
    on a bf16 model that materializes a 2x-size tensor between the conv and
    the stats pass and breaks producer fusion (measured -23% of forward
    throughput, mfu_decomposition.json).  ``FusedBatchNorm`` reduces the
    bf16 activations directly with float32 ACCUMULATION (jnp.mean's dtype
    argument lowers to a bf16-read/f32-accumulate XLA reduce), so the stats
    pass reads half the bytes and fuses with its producer.  Parameters and
    running statistics stay float32 either way.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops import backward as backward_ops

ModuleDef = Any

# Space-to-depth block size for the 224px stem: 2x2 pixel blocks -> 12
# channels, turning the 7x7/s2 stem into a 4x4/s1 conv (see module
# docstring).  The channel order within a block is (di, dj, c) row-major —
# data/pipeline.space_to_depth, space_to_depth() below, and
# s2d_stem_kernel() must all agree on it.
S2D_BLOCK = 2


def space_to_depth(x: jnp.ndarray, block: int = S2D_BLOCK) -> jnp.ndarray:
    """[B, H, W, C] -> [B, H/b, W/b, b*b*C]; works on jnp and np arrays
    (pure reshape/transpose).  Channel index = (di * b + dj) * C + c."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, block * block * c)


def s2d_stem_kernel(kernel7: jnp.ndarray) -> jnp.ndarray:
    """Fold a [7, 7, C, F] stride-2/pad-3 stem kernel into the exact
    [4, 4, 4C, F] stride-1 kernel over space-to-depth input.

    Derivation: output(i,j) sums W[a,b,c]·X[2i+a-3, 2j+b-3, c].  Writing
    the input row as u = 2p + di (p the s2d row, di the in-block offset)
    gives a = 2r + di - 1 for s2d tap r = p - i + 2 ∈ 0..3 — i.e. pad the
    kernel to 8x8 with one leading zero row/col, then regroup [4,2,4,2]
    into taps x in-block offsets.  Pure re-indexing: every product of the
    7x7 conv appears exactly once (plus 4C·F structural zeros), so the
    convolution is exact in every dtype.
    """
    kh, kw, c, f = kernel7.shape
    assert (kh, kw) == (7, 7), f"stem kernel must be 7x7, got {kh}x{kw}"
    padded = jnp.pad(jnp.asarray(kernel7),
                     ((1, 0), (1, 0), (0, 0), (0, 0)))
    k = padded.reshape(4, 2, 4, 2, c, f)          # [r, di, s, dj, c, f]
    k = k.transpose(0, 2, 1, 3, 4, 5)             # [r, s, di, dj, c, f]
    return k.reshape(4, 4, 4 * c, f)


def stem_kernel_from_s2d(kernel4: jnp.ndarray) -> jnp.ndarray:
    """Inverse of ``s2d_stem_kernel``: [4, 4, 4C, F] -> [7, 7, C, F]
    (drops the structural zero row/col)."""
    kh, kw, c4, f = kernel4.shape
    assert (kh, kw) == (4, 4) and c4 % 4 == 0
    c = c4 // 4
    k = kernel4.reshape(4, 4, 2, 2, c, f)         # [r, s, di, dj, c, f]
    k = k.transpose(0, 2, 1, 3, 4, 5)             # [r, di, s, dj, c, f]
    return k.reshape(8, 8, c, f)[1:, 1:]


class FusedBatchNorm(nn.Module):
    """Drop-in BatchNorm whose batch statistics read the activations in
    their COMPUTE dtype (bf16) with float32 accumulation, instead of
    flax's materialize-as-float32-then-reduce (see module docstring).

    Same collections and semantics as the ``nn.BatchNorm`` usage in this
    file: float32 scale/bias params, float32 running mean/var in
    ``batch_stats``, fast-variance formula E[x²]−E[x]² (flax's
    ``use_fast_variance=True`` default), momentum-0.9 EMA update.
    """

    use_running_average: Optional[bool] = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32
    # Cross-device statistics axis: None (the jit path — the partitioner
    # lowers the batch reductions to collectives itself, SyncBatchNorm
    # for free) or a mesh axis name when the module runs inside a
    # shard_map body (the int8 gradient-sync step), where local means
    # must be pmean'd explicitly to keep global-batch semantics.
    axis_name: Optional[str] = None
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        features = x.shape[-1]
        axes = tuple(range(x.ndim - 1))
        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32),
                                (features,))
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32),
                               (features,))
        scale = self.param("scale", self.scale_init, (features,),
                           jnp.float32)
        bias = self.param("bias", self.bias_init, (features,), jnp.float32)

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        elif self.axis_name is None:
            # Training statistics + normalize via the custom-VJP kernel
            # (ops/backward.fused_bn_train): the primal is bit-identical
            # to the inline bf16-reads/f32-accumulation math that lived
            # here (the ``dtype`` reduce argument and in-reduce f32
            # convert — no float32 copy materialized; the SQUARE happens
            # in f32 because E[x²]−E[x]² amplifies bf16 squaring error
            # into a clamped-to-zero variance whenever mean² ≫ var), and
            # the BACKWARD keeps the same discipline instead of XLA's
            # materialize-everything-as-f32 derivation (DESIGN.md §4,
            # parity pinned in tests/test_backward.py).
            y, mean, var = backward_ops.fused_bn_train(
                x, scale, bias, dtype=self.dtype, epsilon=self.epsilon)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
            return y
        else:
            # shard_map body (axis_name set): per-shard partial sums
            # pmean'd into GLOBAL batch statistics — same global-batch
            # BN the jit partitioner derives, up to reduction order.
            # Plain autodiff backward here: this branch only runs on the
            # quantized-gradient path, which is bounded-delta by
            # contract anyway (parallel/mesh.int8_allreduce).
            x_stats = x.astype(self.dtype)
            mean = jax.lax.pmean(
                jnp.mean(x_stats, axes, dtype=jnp.float32), self.axis_name)
            mean2 = jax.lax.pmean(
                jnp.mean(jax.lax.square(x_stats.astype(jnp.float32)),
                         axes), self.axis_name)
            var = jnp.maximum(mean2 - jax.lax.square(mean), 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var

        mul = (scale * jax.lax.rsqrt(var + self.epsilon)).astype(self.dtype)
        sub = (mean.astype(self.dtype) * mul - bias.astype(self.dtype))
        return x.astype(self.dtype) * mul - sub


# Flax auto-names unnamed submodules by CLASS name; the residual blocks'
# norms must keep their "BatchNorm_N" paths so checkpoints (and the torch
# overlay map in utils/pretrained.py) are identical whichever statistics
# path a model was built with — a bf16-stats training run must restore
# into an f32-stats eval model and vice versa.
FusedBatchNorm.__name__ = "BatchNorm"
FusedBatchNorm.__qualname__ = "BatchNorm"

# torch init_params semantics (src/models/utils.py:5-18): conv weights
# kaiming-normal fan_out, linear weights N(0, 1e-3), biases zero.  BatchNorm
# scale=1/bias=0 is the flax default.
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")
dense_kernel_init = nn.initializers.normal(stddev=1e-3)


class S2DStemConv(nn.Module):
    """The s2d stem's 4x4/stride-1 conv with the hand-written backward
    (ops/backward.stem_conv): forward bit-identical to the ``nn.Conv``
    it replaces (same param name/shape/init — checkpoint trees are
    unchanged), backward with bf16 reads and a float32-ACCUMULATED
    weight gradient instead of XLA's bf16-accumulate-then-cast
    derivation (DESIGN.md §4; parity pinned in tests/test_backward.py).
    """

    features: int
    dtype: Any = jnp.float32
    kernel_init: Callable = conv_kernel_init

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (4, 4, x.shape[-1], self.features),
                            jnp.float32)
        return backward_ops.stem_conv(x, kernel, dtype=self.dtype,
                                      padding=((2, 1), (2, 1)))


class BasicBlock(nn.Module):
    """ResNet v1.5 basic block (two 3x3 convs) — resnet18/34."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = None
    norm: ModuleDef = None

    @nn.compact
    def __call__(self, x):
        # 3x3 convs use EXPLICIT (1, 1) padding, not "SAME": for stride-2
        # on even spatial sizes SAME pads (0, 1) while torch's padding=1
        # pads (1, 1) — a one-pixel window shift that silently breaks
        # converted torch checkpoints (tests/test_torch_parity.py).
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides,
                      padding=[(1, 1), (1, 1)])(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = self.norm(scale_init=nn.initializers.ones)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """ResNet v1.5 bottleneck (1x1 -> strided 3x3 -> 1x1 x4) — resnet50.

    The stride lives on the 3x3 conv, matching torchvision's v1.5 used by
    the reference (resnet_hacks.py docstring notes torchvision is v1.5).
    """

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = None
    norm: ModuleDef = None

    @nn.compact
    def __call__(self, x):
        # Same explicit-padding rule as BasicBlock for the strided 3x3.
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides,
                      padding=[(1, 1), (1, 1)])(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.ones)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class ResNetEncoder(nn.Module):
    """Backbone producing the pooled final embedding (fc removed, mirroring
    ``self.encoder.fc = nn.Identity()`` at resnet_simclr.py:21)."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_filters: int = 64
    cifar_stem: bool = False
    stem: str = "default"  # "default" | "s2d" (224px path only)
    bn_stats_dtype: Any = None  # None/f32 -> flax BatchNorm; bf16 -> fused
    # BN cross-device statistics axis for shard_map bodies (the int8
    # gradient-sync train step) — None under plain jit, where the
    # partitioner derives the collective itself.
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype,
            kernel_init=conv_kernel_init)
        fused_stats = self.bn_stats_dtype == jnp.bfloat16
        norm = functools.partial(
            FusedBatchNorm if fused_stats else nn.BatchNorm,
            use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.axis_name)

        x = x.astype(self.dtype)
        if self.cifar_stem:
            # SimCLR CIFAR stem: 3x3 stride-1 conv, no max pool
            # (resnet_hacks.py:31-35).
            x = conv(self.num_filters, (3, 3), (1, 1), name="conv_stem")(x)
            x = norm(name="bn_stem")(x)
            x = nn.relu(x)
        elif self.stem == "s2d":
            if x.shape[-1] == 3:
                # Host didn't pre-transform (resident pools, epoch-scan
                # gathers): the layout change is a free on-device reshape
                # that XLA fuses with the conv's input read.
                x = space_to_depth(x)
            # Exact refactoring of the 7x7/s2 stem: 4x4/s1 over 2x2-block
            # channels, explicit (2, 1) padding = the 7x7's pad-3 window
            # in s2d coordinates (see s2d_stem_kernel).  S2DStemConv is
            # forward-identical to the nn.Conv it replaced (same param
            # tree) with the hand-written f32-accumulating backward.
            x = S2DStemConv(self.num_filters, dtype=self.dtype,
                            name="conv_stem")(x)
            x = norm(name="bn_stem")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2),
                            padding=[(1, 1), (1, 1)])
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_stem")(x)
            x = norm(name="bn_stem")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2),
                            padding=[(1, 1), (1, 1)])

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm,
                    name=f"stage{i + 1}_block{j}")(x)

        # Global average pool -> final embedding, float32 for the head and
        # for downstream acquisition math (margins, pairwise distances).
        x = jnp.mean(x, axis=(1, 2))
        return x.astype(jnp.float32)


class SSLClassifier(nn.Module):
    """Encoder + separate linear head (resnet_simclr.py:20-22).

    Forward modes:
      * ``apply(vars, x)``                      -> logits
      * ``apply(vars, x, return_features=True)``-> (logits, embedding)
      * ``apply(vars, emb, method="head")``     -> logits from an embedding
        (the reference's ``specify_input_layer='finalembed'``).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    cifar_stem: bool = False
    stem: str = "default"
    bn_stats_dtype: Any = None
    # BN cross-device axis for shard_map bodies; the trainer clones the
    # model with this set when building the int8 gradient-sync step
    # (``model.clone(axis_name=...)`` — parameters are unaffected).
    axis_name: Optional[str] = None
    freeze_feature: bool = False
    dtype: Any = jnp.float32

    def setup(self):
        self.encoder = ResNetEncoder(
            stage_sizes=self.stage_sizes, block_cls=self.block_cls,
            cifar_stem=self.cifar_stem, stem=self.stem,
            bn_stats_dtype=self.bn_stats_dtype,
            axis_name=self.axis_name, dtype=self.dtype,
            name="encoder")
        self.linear = nn.Dense(
            self.num_classes, kernel_init=dense_kernel_init,
            bias_init=nn.initializers.zeros, name="linear")

    def __call__(self, x, train: bool = True, return_features: bool = False):
        embedding = self.encoder(x, train=train)
        if self.freeze_feature:
            # Stop-gradient on the backbone output (resnet_simclr.py:36-37);
            # combined with eval-mode BN in the trainer this freezes the
            # feature extractor for linear evaluation.
            embedding = jax.lax.stop_gradient(embedding)
        logits = self.linear(embedding)
        if return_features:
            return logits, embedding
        return logits

    def head(self, embedding):
        return self.linear(embedding)

    @property
    def embed_dim(self) -> int:
        mult = 4 if self.block_cls is BottleneckBlock else 1
        return 64 * 2 ** (len(self.stage_sizes) - 1) * mult


def _make(stage_sizes, block_cls, num_classes, cifar_stem, freeze_feature,
          dtype, stem, bn_stats_dtype):
    if stem == "s2d" and cifar_stem:
        raise ValueError("the s2d stem refactors the 7x7/s2 ImageNet stem; "
                         "the CIFAR stem (3x3/s1) has nothing to fold")
    if stem not in ("default", "s2d"):
        raise ValueError(f"unknown stem {stem!r}; expected 'default'/'s2d'")
    return SSLClassifier(
        stage_sizes=tuple(stage_sizes), block_cls=block_cls,
        num_classes=num_classes, cifar_stem=cifar_stem, stem=stem,
        bn_stats_dtype=bn_stats_dtype, freeze_feature=freeze_feature,
        dtype=dtype)


def resnet18(num_classes: int, cifar_stem: bool = False,
             freeze_feature: bool = False, dtype: Any = jnp.float32,
             stem: str = "default",
             bn_stats_dtype: Any = None) -> SSLClassifier:
    return _make([2, 2, 2, 2], BasicBlock, num_classes, cifar_stem,
                 freeze_feature, dtype, stem, bn_stats_dtype)


def resnet50(num_classes: int, cifar_stem: bool = False,
             freeze_feature: bool = False, dtype: Any = jnp.float32,
             stem: str = "default",
             bn_stats_dtype: Any = None) -> SSLClassifier:
    return _make([3, 4, 6, 3], BottleneckBlock, num_classes, cifar_stem,
                 freeze_feature, dtype, stem, bn_stats_dtype)
