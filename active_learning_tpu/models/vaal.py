"""VAAL's auxiliary models: the WAE-style VAE and the latent discriminator.

Reference: src/query_strategies/vae.py:18-102 (4-conv encoder / 3-deconv
decoder + 1x1 output conv, fc_mu/fc_logvar heads, reparameterization) and
vaal_discriminator.py:5-31 (z -> 512 -> 512 -> 1 MLP + sigmoid).

Shape bookkeeping: the reference's ``latent_scale`` (1 for CIFAR, 2 for
ImageNet, vaal_sampler.py:23-29) only encodes the post-encoder spatial size
for a 32 / 64 pixel input; here the flatten is dynamic and the decoder's
start resolution is ``crop // 8``, so any crop divisible by 16 works and
the two reference cases reproduce exactly (32 -> 1024*2*2 flat, decoder
4x4 start; 64 -> 1024*4*4 flat, 8x8 start).

Init parity: the reference applies kaiming-normal to nn.Conv2d/nn.Linear
only — its ConvTranspose2d layers keep torch defaults because the
isinstance check misses them (vae.py:105-108); deconvs here likewise keep
the Flax default init.  NHWC layout, float32 (these nets are tiny next to
the classifier).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

kaiming_init = nn.initializers.variance_scaling(2.0, "fan_in", "normal")

_ENC_FEATURES = (128, 256, 512, 1024)
_DEC_FEATURES = (512, 256, 128)
CROP_HW = 64  # vae.py:6-7; inputs smaller than this are used whole


class VAE(nn.Module):
    """Conv VAE over ``crop x crop`` inputs (vae.py:18-102)."""

    z_dim: int = 32
    nc: int = 3
    crop: int = 32

    def setup(self):
        assert self.crop % 16 == 0 or self.crop in (32,), (
            "crop must be divisible by 16")
        self.enc_convs = [
            nn.Conv(f, (4, 4), (2, 2), padding=[(1, 1), (1, 1)],
                    use_bias=False, kernel_init=kaiming_init,
                    name=f"enc_conv{i}")
            for i, f in enumerate(_ENC_FEATURES)]
        self.enc_bns = [
            nn.BatchNorm(momentum=0.9, epsilon=1e-5, name=f"enc_bn{i}")
            for i in range(len(_ENC_FEATURES))]
        self.fc_mu = nn.Dense(self.z_dim, kernel_init=kaiming_init,
                              name="fc_mu")
        self.fc_logvar = nn.Dense(self.z_dim, kernel_init=kaiming_init,
                                  name="fc_logvar")

        start = self.crop // 8
        self.dec_dense = nn.Dense(1024 * start * start,
                                  kernel_init=kaiming_init, name="dec_dense")
        # torch ConvTranspose2d(k=4, s=2, p=1) doubles the spatial size; in
        # flax's conv_transpose the padding applies to the dilated input, so
        # the equivalent explicit padding is k-1-p = 2 per side.
        self.dec_deconvs = [
            nn.ConvTranspose(f, (4, 4), (2, 2), padding=((2, 2), (2, 2)),
                             use_bias=False, name=f"dec_deconv{i}")
            for i, f in enumerate(_DEC_FEATURES)]
        self.dec_bns = [
            nn.BatchNorm(momentum=0.9, epsilon=1e-5, name=f"dec_bn{i}")
            for i in range(len(_DEC_FEATURES))]
        self.dec_out = nn.Conv(self.nc, (1, 1), kernel_init=kaiming_init,
                               name="dec_out")

    def encode(self, x, train: bool = True):
        for conv, bn in zip(self.enc_convs, self.enc_bns):
            x = nn.relu(bn(conv(x), use_running_average=not train))
        x = x.reshape((x.shape[0], -1))
        return self.fc_mu(x), self.fc_logvar(x)

    def decode(self, z, train: bool = True):
        start = self.crop // 8
        x = self.dec_dense(z).reshape((-1, start, start, 1024))
        for deconv, bn in zip(self.dec_deconvs, self.dec_bns):
            x = nn.relu(bn(deconv(x), use_running_average=not train))
        return self.dec_out(x)

    def __call__(self, x, eps_key=None, train: bool = True):
        """-> (recon, z, mu, logvar).  ``eps_key`` drives the
        reparameterization draw (vae.py:90-96); None means z = mu (used by
        the scoring pass, which only consumes mu anyway)."""
        mu, logvar = self.encode(x, train=train)
        if eps_key is None:
            z = mu
        else:
            std = jnp.exp(0.5 * logvar)
            z = mu + std * jax.random.normal(eps_key, mu.shape, mu.dtype)
        recon = self.decode(z, train=train)
        return recon, z, mu, logvar


class Discriminator(nn.Module):
    """Latent-space adversary (vaal_discriminator.py:5-21)."""

    z_dim: int = 32

    @nn.compact
    def __call__(self, z):
        z = nn.relu(nn.Dense(512, kernel_init=kaiming_init)(z))
        z = nn.relu(nn.Dense(512, kernel_init=kaiming_init)(z))
        z = nn.Dense(1, kernel_init=kaiming_init)(z)
        return nn.sigmoid(z)


def crop_size_for(image_hw: int) -> int:
    """The reference crops >=64px inputs to 64 and uses smaller inputs
    whole (vae.py:65-78)."""
    return CROP_HW if image_hw >= CROP_HW else image_hw


def random_crop(x: jnp.ndarray, crop: int, key: jax.Array) -> jnp.ndarray:
    """One shared crop window for the whole batch AND for every VAE call in
    the same training step — the reference seeds np.random with a per-batch
    crop seed, so its labeled/unlabeled/discriminator forwards all see the
    same window (vaal_sampler.py:214, vae.py:62-78)."""
    b, h, w, c = x.shape
    if h <= crop and w <= crop:
        return x
    oh = jax.random.randint(key, (), 0, h - crop + 1)
    ow = jax.random.randint(jax.random.fold_in(key, 1), (), 0, w - crop + 1)
    return jax.lax.dynamic_slice(x, (0, oh, ow, 0), (b, crop, crop, c))
