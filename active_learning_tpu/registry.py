"""Explicit name->factory registries.

The reference resolves strategies, optimizers, schedulers, and metrics from
strings via ``eval()`` (src/query_strategies/get_strategy.py:17,
src/query_strategies/strategy.py:345-350, src/utils/evaluation.py:103) and
imports arg pools via ``exec()`` (src/main_al.py:48).  This module replaces
all of that with typed registries.
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, Iterator, TypeVar

T = TypeVar("T")


class Registry(Generic[T]):
    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, T] = {}

    def register(self, name: str, obj: T = None):
        """Register ``obj`` under ``name``; usable as a decorator."""
        if obj is not None:
            self._add(name, obj)
            return obj

        def deco(o: T) -> T:
            self._add(name, o)
            return o

        return deco

    def _add(self, name: str, obj: T) -> None:
        if name in self._entries:
            raise KeyError(f"{self.kind} '{name}' already registered")
        self._entries[name] = obj

    def get(self, name: str) -> T:
        try:
            return self._entries[name]
        except KeyError:
            known = ", ".join(sorted(self._entries))
            raise KeyError(
                f"Unknown {self.kind} '{name}'. Known: {known}") from None

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def __iter__(self) -> Iterator[str]:
        return iter(sorted(self._entries))

    def names(self):
        return sorted(self._entries)


# Global registries, populated by the defining modules on import.
STRATEGIES: Registry = Registry("strategy")        # name -> Strategy subclass
MODELS: Registry = Registry("model")               # name -> model factory
DATASETS: Registry = Registry("dataset")           # name -> dataset-triple factory
ARG_POOLS: Registry = Registry("arg_pool")         # name -> {dataset: TrainConfig}
OPTIMIZERS: Registry = Registry("optimizer")       # name -> optax factory
SCHEDULERS: Registry = Registry("scheduler")       # name -> per-epoch lr fn factory
