"""Softmax-uncertainty acquisition: least-confidence and smallest-margin.

Reference: src/query_strategies/confidence_sampler.py:8-47 and
margin_sampler.py:8-45.  Both run one mesh-parallel scoring pass
(strategies/scoring.make_prob_stats_step) instead of the reference's
single-GPU loader walk; confidence and margin come out of the same fused
top-2 kernel.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .base import Strategy, register_strategy


class _ScoreAscendingSampler(Strategy):
    """Shared shape: score every available example, take the ``budget``
    smallest."""

    score_key: str = ""

    def speculative_scoring_plan(self):
        """The coming query scores the UNSHUFFLED available set — a pure
        function of the pool masks, no rng anywhere — so the pipelined
        round can pre-score it chunk by chunk during the fit's patience
        tail (experiment/pipeline.py)."""
        idxs = self.pool.available_query_idxs(shuffle=False)
        if len(idxs) == 0:
            return None
        return {"kind": "prob_stats", "keys": (self.score_key,),
                "idxs": idxs}

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        idxs = self.available_query_idxs(shuffle=False)
        if len(idxs) == 0:
            return idxs, 0
        scores = self.collect_scores(idxs, "prob_stats",
                                     keys=(self.score_key,))[self.score_key]
        budget = int(min(len(idxs), budget))
        order = np.argsort(scores, kind="stable")[:budget]
        return idxs[order], budget


@register_strategy("ConfidenceSampler")
class ConfidenceSampler(_ScoreAscendingSampler):
    """Smallest top-1 softmax probability first (confidence_sampler.py:33-36).

    Deliberately FIXES the reference's bug at confidence_sampler.py:41,
    which re-indexes the length-N confidence vector by pool indices
    (``confidence[idxs_for_query]``) before sorting — selecting by a
    scrambled score.  Here scores align 1:1 with ``idxs``.
    """

    score_key = "confidence"


@register_strategy("MarginSampler")
class MarginSampler(_ScoreAscendingSampler):
    """Smallest (top-1 − top-2) softmax probability margin first
    (margin_sampler.py:33-44)."""

    score_key = "margin"
