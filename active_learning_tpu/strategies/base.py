"""The active-learning Strategy engine.

TPU-native counterpart of the reference's ``Strategy`` base class
(src/query_strategies/strategy.py:21-485).  The reference interleaves pool
bookkeeping, DDP process management, training, evaluation, and checkpointing
in one 485-line class; here those concerns live in dedicated modules
(pool.PoolState, train.Trainer, train.checkpoint, utils.metrics) and
``Strategy`` composes them into the reference's public surface:

    query(budget) -> (labeled_idxs, cost)   [abstract; per-sampler]
    update(labeled_idxs, cost)              strategy.py:459-485
    init_network_weights()                  strategy.py:175-200
    train()                                 strategy.py:286-381
    load_best_ckpt()                        strategy.py:202-206
    test()                                  strategy.py:211-247

Key architectural differences (deliberate, TPU-first):
  * ONE persistent JAX runtime and mesh for the whole experiment — no
    per-round mp.spawn/NCCL process groups (strategy.py:288-315).
  * Pool scoring is mesh-parallel (strategies/scoring.py): the reference
    scores on a single GPU in the parent process (SURVEY.md §2 parallelism
    table).
  * All randomness flows from one np.random.Generator + JAX PRNG, so a
    round is exactly reproducible from saved state (the reference uses the
    global np.random / torch seeds).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Tuple

import jax
import numpy as np

from .. import faults
from ..config import ExperimentConfig, TrainConfig
from ..data.core import Dataset
from ..pool import PoolState
from ..registry import STRATEGIES
from ..telemetry import diagnostics as diag_lib
from ..train import checkpoint as ckpt_lib
from ..train.trainer import Trainer, TrainState
from ..utils.logging import get_logger
from ..utils.metrics import MetricsSink, NullSink
from . import scoring

# Pool scoring is stateless (consumes no rng, reads frozen weights), so
# a whole-pass retry after a transient failure — a dead prefetch feeder
# thread, an injected feed_worker fault, a flaky H2D — reproduces the
# same scores bit for bit.  One retry: a pass that fails twice is not
# transient; the driver's degradation ladder takes over.
_SCORE_RETRY = faults.RetryPolicy(site="pool_score",
                                  classify=faults.classify_exception,
                                  max_attempts=2)


class Strategy:
    """Base class: owns the model state, pool state, trainer, and metrics
    sink for one experiment; subclasses implement ``query``.

    Args mirror the reference constructor (strategy.py:74-124) in spirit:
    the dataset triple, the model + trainer, pool state, and configs.
    """

    def __init__(
        self,
        train_set: Dataset,
        al_set: Dataset,
        test_set: Optional[Dataset],
        model,
        trainer: Trainer,
        pool: PoolState,
        cfg: ExperimentConfig,
        train_cfg: TrainConfig,
        sink: Optional[MetricsSink] = None,
        rng: Optional[np.random.Generator] = None,
    ):
        self.train_set = train_set
        self.al_set = al_set
        self.test_set = test_set
        self.model = model
        self.trainer = trainer
        self.pool = pool
        self.cfg = cfg
        self.train_cfg = train_cfg
        self.sink = sink if sink is not None else NullSink()
        self.rng = rng if rng is not None else np.random.default_rng(cfg.run_seed)
        self.logger = get_logger()

        self.num_classes = al_set.num_classes
        self.mesh = trainer.mesh
        self.state: Optional[TrainState] = None
        self.best_epoch: int = 0
        self.best_perf: float = 0.0
        # The last test() accuracy — the driver's run_report rows read
        # it (test() already computes it; storing beats re-plumbing the
        # return through the round loop).
        self.last_test_acc: Optional[float] = None
        # The experiment-truth diagnostics layer (telemetry/diagnostics,
        # DESIGN.md §13): per-round score histograms + drift, selection
        # composition, pick distances, calibration — all computed from
        # host arrays that already exist.  None when disabled; every
        # hot-path hook below is then a single None check (<2.5µs/call,
        # pinned in tests/test_diagnostics.py), and picks/scores are
        # bit-identical either way.
        tele = getattr(cfg, "telemetry", None)
        self.diagnostics = (
            diag_lib.RoundDiagnostics(num_classes=self.num_classes)
            if tele is not None and getattr(tele, "enabled", False)
            and getattr(tele, "diagnostics", False) else None)
        # Device-resident pool cache: in-memory pool images live on device
        # for the WHOLE experiment (scoring.collect_pool fast path).  It
        # is the TRAINER'S cache, shared with evaluation, so one upload
        # serves every round's every sampler AND the per-epoch validation.
        self._resident_pool: Dict = trainer.resident_pool
        # True only for the first train() after a genuine experiment
        # resume (the driver sets it): that is the one fit allowed to
        # consume a mid-round fit state from disk; trainer.fit discards
        # stale states otherwise.
        self.resume_next_fit: bool = False
        # The pipelined-round coordinator (experiment/pipeline.py), or
        # None for the sequential loop.  The driver installs it; when
        # present, collect_scores consumes speculative chunk scores and
        # train() wires the best-ckpt publish into the fit.
        self.pipeline = None
        self._score_steps: Dict[str, Callable] = {}
        # Per-experiment init key; split once per re-init so every round's
        # random re-initialization is fresh but reproducible.
        self._init_key = jax.random.PRNGKey(int(self.rng.integers(2 ** 31)))

    # -- identity --------------------------------------------------------

    @property
    def round(self) -> int:
        return self.pool.round

    @round.setter
    def round(self, value: int) -> None:
        self.pool.round = int(value)

    @property
    def cumulative_cost(self) -> float:
        return self.pool.cumulative_cost

    @property
    def exp_hash(self) -> str:
        return self.cfg.exp_hash or "no_hash"

    # -- pool views (strategy.py:126-163) --------------------------------

    def available_query_idxs(self, shuffle: bool = True) -> np.ndarray:
        return self.pool.available_query_idxs(shuffle=shuffle, rng=self.rng)

    def available_query_mask(self) -> np.ndarray:
        return self.pool.available_mask()

    def already_labeled_idxs(self, shuffle: bool = False) -> np.ndarray:
        return self.pool.labeled_idxs(shuffle=shuffle, rng=self.rng)

    def already_labeled_mask(self) -> np.ndarray:
        return self.pool.labeled_mask()

    # -- weights (strategy.py:165-206) ------------------------------------

    def weight_paths(self) -> Dict[str, str]:
        return ckpt_lib.weight_paths(self.cfg.ckpt_path, self.cfg.exp_name,
                                     self.exp_hash, self.round)

    def init_network_weights(self) -> None:
        """Fresh random init every round (so the linear head always resets,
        strategy.py:182-184), then overlay a pretrained SSL/transfer ckpt if
        one is configured (strategy.py:185-196)."""
        self._init_key, sub = jax.random.split(self._init_key)
        sample = self.train_set.gather(np.zeros(1, dtype=np.int64))
        if self.state is None:
            self.state = self.trainer.init_state(sub, sample)
        else:
            variables = self.model.init(sub, sample.astype(np.float32),
                                        train=False)
            self.state = self.trainer.replace_variables(self.state, variables)
        if self.train_cfg.has_pretrained:
            from ..utils.pretrained import apply_pretrained
            variables = apply_pretrained(
                dict(self.state.variables), self.train_cfg.pretrained)
            self.state = self.trainer.replace_variables(self.state, variables)
            self.logger.info(
                f"Initialized network weights from "
                f"{self.train_cfg.pretrained.path}")
        else:
            self.logger.info("Initialized Network Weights Randomly.")

    def load_best_ckpt(self) -> None:
        path = self.weight_paths()["best_ckpt"]
        self.logger.info(f"Loading best ckpt so far from: {path}")
        variables = ckpt_lib.load_variables(path, like=self.state.variables)
        self.state = self.trainer.replace_variables(self.state, variables)

    # -- auxiliary round-level state (resume seam) ------------------------

    def aux_state_bytes(self) -> Optional[bytes]:
        """Serialized sampler-owned state beyond the pool/model (e.g.
        VAAL's VAE+discriminator) for the round-level experiment save.
        None = nothing to persist.  The reference keeps such state for
        free by pickling the whole strategy object
        (src/utils/resume_training.py:38-52)."""
        return None

    def restore_aux_state(self, data: bytes) -> None:
        """Inverse of aux_state_bytes, called during experiment resume."""

    # -- the four verbs ---------------------------------------------------

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        raise NotImplementedError

    def speculative_scoring_plan(self) -> Optional[Dict]:
        """The NEXT query's scoring pass as a plan the pipelined round's
        speculative scorer can run ahead of time, or None when there is
        nothing safely speculable.

        Contract (experiment/pipeline.py): the plan must be computed
        WITHOUT consuming any rng and must name EXACTLY the
        (kind, keys, idxs) the coming ``query`` will hand to
        ``collect_scores`` — the pipeline serves the speculative result
        only on an exact match, so a wrong plan degrades to the
        sequential pass, never to a wrong score.  Samplers whose scored
        index order is rng-dependent (partitioned variants, subset
        caps) or who score with non-checkpoint state (VAAL's VAE)
        return None.  Keys: ``kind`` (a _get_score_step name), ``keys``
        (tuple), ``idxs`` (int64 array)."""
        return None

    def update(self, labeled_idxs, cur_cost: float) -> None:
        """Mark queried examples labeled, spend budget, emit the audit
        trail (strategy.py:459-485)."""
        labeled_idxs = np.asarray(labeled_idxs, dtype=np.int64).reshape(-1)
        # Selection composition (class balance / novelty) must read the
        # labeled mask BEFORE this update flips it; one gated call.
        self._record_pick_diagnostics(labeled_idxs)
        self.pool.update(labeled_idxs, cur_cost)
        self.sink.log_metric("cumulative_budget", self.pool.cumulative_cost,
                             step=self.round)
        self.logger.info(
            f"Cumulative budget used on round {self.round} = "
            f"{self.pool.cumulative_cost}")
        self.sink.log_asset(f"labeled_idxs_on_rd_{self.round}",
                            ",".join(str(int(e)) for e in labeled_idxs))

    def train(self) -> None:
        """Per-round training with validation + early stopping.  The mesh
        is persistent — this replaces the whole mp.spawn/DDP stack
        (strategy.py:286-381)."""
        if self.state is None:
            self.init_network_weights()
        labeled = self.already_labeled_idxs()
        self.logger.info(f"Starting training on round {self.round}")
        if self.pipeline is not None:
            # The select-time prefetch must never run INTO the fit it
            # warmed — on the last round (which never arms) this is the
            # only join.
            self.pipeline.join_prefetch()

        def metric_cb(name: str, value: float, step: int) -> None:
            self.sink.log_metric(name, value, step=step)

        result = self.trainer.fit(
            self.state,
            self.train_set,
            labeled,
            self.al_set,
            self.pool.eval_idxs,
            n_epoch=self.cfg.n_epoch,
            es_patience=self.cfg.early_stop_patience,
            rng=self.rng,
            round_idx=self.round,
            weight_paths=self.weight_paths(),
            metric_cb=metric_cb,
            resume_fit_state=self.resume_next_fit,
            # The in-process leg of the best-ckpt bus: the pipelined
            # round's speculative scorer starts on a new best the moment
            # it is snapshotted, without waiting for the periodic disk
            # publish.
            on_best=(self.pipeline.publish_best
                     if self.pipeline is not None else None),
        )
        self.resume_next_fit = False
        if self.pipeline is not None:
            # Pin the FINAL (round, best_epoch) tag: speculative chunks
            # scored from any other checkpoint are now dead, and the
            # scorer keeps working from the final one through
            # load_best_ckpt/test until the next query consumes it.
            self.pipeline.finalize(self.round, result.best_epoch)
        self.state = result.state
        self.best_epoch = result.best_epoch
        # The fit's best validation accuracy: collapse detectors (e.g.
        # the evidence protocol's re-init guard,
        # scripts/cifar10_evidence.py) read it to tell a dead round —
        # best-of-fit at chance — from a trained one.
        self.best_perf = float(result.best_perf)
        self.logger.info(f"Finished training on round {self.round}")

    def test(self) -> Optional[float]:
        """Test-set evaluation + the reference's metric schema: round- and
        budget-keyed accuracy plus the per-class asset
        (strategy.py:211-247)."""
        if self.test_set is None:
            self.logger.info("Skipped testing loop, no testing dataset found.")
            return None
        perf = self.trainer.evaluate(self.state, self.test_set,
                                     np.arange(len(self.test_set)))
        acc = float(perf["accuracy"])
        self.last_test_acc = acc
        # Calibration (ECE + confidence histogram) piggybacks on the
        # eval pass's additive per-bin counts — no second pass.
        self._record_calibration_diagnostics(perf)
        top5 = float(perf["top_5_accuracy"])
        byclass = np.asarray(perf["accuracy_byclass"])
        order = np.argsort(byclass)
        k = int(min(5, len(byclass)))
        self.logger.info(
            f"Test performance at round {self.round} is {acc * 100:.2f}%")
        self.logger.info(
            f"Best {k} classes: "
            f"{ {int(i): f'{byclass[i] * 100:.2f}' for i in order[-k:]} }")
        self.logger.info(
            f"Worst {k} classes: "
            f"{ {int(i): f'{byclass[i] * 100:.2f}' for i in order[:k]} }")
        self.logger.info(
            f"Test top 5 acc at round {self.round} is {top5 * 100:.2f}%")
        self.sink.log_metrics(
            {"rd_test_accuracy": acc, "rd_test_top5_accuracy": top5},
            step=self.round)
        self.sink.log_metrics(
            {"budget_test_accuracy": acc, "budget_test_top5_accuracy": top5},
            step=self.pool.cumulative_cost)
        self.sink.log_asset(
            f"test_acc_byclass_rd_{self.round}",
            ",".join(f"{e:.2f}" for e in byclass))
        return acc

    # -- scoring infrastructure -------------------------------------------

    def _score_batch_size(self) -> int:
        """Global scoring batch: explicit config wins; auto keeps the
        reference's test-loader batch on CPU and raises it to a
        row-size-scaled per-chip floor on accelerators (see
        Trainer.eval_batch_size — scoring is per-example under eval BN,
        so this is throughput-only)."""
        explicit = self.train_cfg.score_batch_size
        if explicit:
            return self.trainer.padded_batch_size(int(explicit))
        # Auto: ONE policy with evaluation (Trainer.eval_batch_size) —
        # the floor must never diverge between the two passes.
        return self.trainer.padded_batch_size(
            self.trainer.eval_batch_size(self.al_set))

    def _get_score_step(self, kind: str) -> Callable:
        if kind not in self._score_steps:
            view = self.al_set.view
            if kind == "prob_stats":
                self._score_steps[kind] = scoring.make_prob_stats_step(
                    self.model, view)
            elif kind == "embed":
                self._score_steps[kind] = scoring.make_embed_step(
                    self.model, view)
            elif kind == "embed_margin":
                self._score_steps[kind] = scoring.make_embed_step(
                    self.model, view, with_probs=True)
            elif kind == "mase":
                self._score_steps[kind] = scoring.make_mase_step(
                    self.model, view)
            elif kind == "badge":
                self._score_steps[kind] = scoring.make_badge_step(
                    self.model, view)
            elif kind == "badge_pool":
                self._score_steps[kind] = scoring.make_badge_step(
                    self.model, view, pool_512=True)
            else:
                raise KeyError(f"unknown scoring kind '{kind}'")
            # Compile accounting (telemetry/runtime.py): scoring steps
            # join the trainer's in the generalized jit-cache counter —
            # a nonzero per-round miss delta after round 1 is a shape
            # leak.  No-op without an installed run.
            from ..telemetry import runtime as tele_runtime
            tele_runtime.get_run().register_jit(
                f"score_{kind}@{id(self):x}", self._score_steps[kind])
        return self._score_steps[kind]

    def collect_scores(self, idxs: np.ndarray, kind: str,
                       keys=None) -> Dict[str, np.ndarray]:
        """Mesh-parallel scoring pass over ``al_set[idxs]`` returning host
        arrays aligned with ``idxs``.  With telemetry on, the pass's
        pool-scan rate lands in the sink as ``pool_rows_per_sec`` —
        the acquisition-side counterpart of the trainer's imgs_per_sec.

        Under a pipelined round the speculative scorer is consulted
        first: chunks it pre-scored with the FINAL best checkpoint are
        served as-is and the rest are completed inline — bit-identical
        either way (experiment/pipeline.py's correctness contract), so
        speculation only ever changes wall-clock."""
        from ..telemetry import runtime as tele_runtime
        bs = self._score_batch_size()
        if self.pipeline is not None:
            out = self.pipeline.consume(kind, keys, np.asarray(idxs), bs,
                                        self.state.variables)
            if out is not None:
                # Score histogram from the consume path's per-chunk
                # partials (bit-equal to the monolithic add — pinned).
                self._record_score_diagnostics(
                    out, self.pipeline.last_consume.get("score_hist"))
                if tele_runtime.get_run().train_metrics:
                    self.sink.log_metric(
                        "spec_hit_frac",
                        self.pipeline.last_consume.get("hit_frac", 0.0),
                        step=self.round)
                    # The same scan-rate metric the sequential pass
                    # emits, over the scoring COMPUTE the hand-over
                    # actually cost (served chunks' scorer walls +
                    # inline completions) — most of it hidden in the
                    # fit, but the rate stays comparable across modes.
                    score_s = self.pipeline.last_consume.get("score_s", 0)
                    if score_s > 0:
                        self.sink.log_metric(
                            "pool_rows_per_sec",
                            round(len(idxs) / score_s, 1),
                            step=self.round)
                return out
        loader = self.train_cfg.loader_te
        t0 = time.perf_counter()
        out = _SCORE_RETRY.call(
            scoring.collect_pool,
            self.al_set, idxs, bs,
            self._get_score_step(kind), self.state.variables, self.mesh,
            num_workers=loader.num_workers, prefetch=loader.prefetch,
            keys=keys, dispatch_lock=self.trainer.dispatch_lock,
            **self._resident_kwargs())
        dt = time.perf_counter() - t0
        if tele_runtime.get_run().train_metrics and dt > 0:
            self.sink.log_metric("pool_rows_per_sec",
                                 round(len(idxs) / dt, 1), step=self.round)
        self._record_score_diagnostics(out)
        return out

    # -- experiment-truth diagnostics hooks (telemetry/diagnostics) -------
    #
    # Each hook is ONE flag check when diagnostics are off (the pinned
    # <2.5µs/call off-path bound) and pure host-array math when on — the
    # diagnostics-inert lint (scripts/al_lint.py) statically forbids
    # anything heavier from growing here.

    def _record_score_diagnostics(self, out: Dict[str, np.ndarray],
                                  premerged=None) -> None:
        """Fold a scoring pass's scalar acquisition scores into the
        round's histogram.  ``premerged``: the pipelined consume path's
        per-chunk partial sums ({key: ScoreHistogram}), used as-is."""
        if self.diagnostics is None:
            return
        key = diag_lib.primary_score_key(out)
        if key is None:
            return
        if premerged is not None and key in premerged:
            self.diagnostics.observe_histogram(key, premerged[key])
        else:
            self.diagnostics.observe_scores(key, out[key])

    def _record_pick_dist_diagnostics(self, dists) -> None:
        """k-center pick distances, straight out of the selection scan
        (strategies/kcenter.LAST_PICK_DISTS)."""
        if self.diagnostics is None or dists is None:
            return
        self.diagnostics.observe_pick_dists(dists)

    def _record_pick_diagnostics(self, labeled_idxs: np.ndarray) -> None:
        """Selection composition for this round's picks (class balance
        and novelty need oracle labels — simulated AL always has them)."""
        if self.diagnostics is None or len(labeled_idxs) == 0:
            return
        targets = getattr(self.al_set, "targets", None)
        if targets is not None:
            targets = np.asarray(targets)[:len(self.al_set)]
        self.diagnostics.observe_picks(labeled_idxs, targets,
                                       self.pool.labeled_mask())

    def _record_calibration_diagnostics(self, perf: Dict) -> None:
        if self.diagnostics is None or "cal_count" not in perf:
            return
        self.diagnostics.observe_calibration(
            perf["cal_count"], perf["cal_correct"], perf["cal_conf_sum"])

    def _resident_kwargs(self) -> Dict:
        """collect_pool kwargs for the device-resident pool: one gating
        convention (a resolved budget of 0 disables) for every sampler,
        including VAAL's own scoring pass.  The budget is the TRAINER'S
        resolved one (auto-sized from HBM headroom when the config is
        None — pool residency is the default, not an override), and the
        host fallback pre-transforms batches for s2d-stem models."""
        rb = self.trainer.resident_budget
        # A pool pinned before an auto-budget refresh shrank rb to 0 must
        # keep its fast path (same rule as trainer.evaluate): its bytes
        # stay in HBM either way, so streaming would pay twice.
        have_pinned = bool(self._resident_pool.get("images"))
        return {"resident_cache": (self._resident_pool
                                   if rb or have_pinned else None),
                "resident_max_bytes": rb,
                "host_s2d": getattr(self.model, "stem",
                                    "default") == "s2d",
                # The trainer's resolved resident layout (DESIGN.md
                # §2b): every sampler's scoring pass pins/reads the
                # shared pool in the SAME layout training does.
                "pool_sharding": self.trainer.pool_sharding}


def register_strategy(name: str):
    """Decorator: register a Strategy subclass under its reference name
    (replaces the eval()-based get_strategy, get_strategy.py:16-17)."""

    def deco(cls):
        STRATEGIES.register(name, cls)
        cls.name = name
        return cls

    return deco
