"""Margin-clustering acquisition: HAC clusters + round-robin min-margin.

Reference: src/query_strategies/margin_clustering_sampler.py:9-90
(arXiv:2107.14263).  One mesh-parallel pass produces embeddings AND softmax
margins (the reference walks a DataLoader computing both per batch,
:23-44); agglomerative clustering stays on host (sklearn — it is inherently
sequential and runs once), and the round-robin selection is cheap index
math.

Cluster-cache semantics preserved exactly (:56-61, :89): cluster once on
the first query and carry assignments forward with queried examples
removed — valid because ``available_query_idxs(shuffle=False)`` is sorted
and shrinks by exactly the queried examples each round.  With a
``subset_unlabeled`` cap the subset is re-drawn and re-clustered every
round.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .base import Strategy, register_strategy

N_CLUSTERS = 20  # margin_clustering_sampler.py:59


@register_strategy("MarginClusteringSampler")
class MarginClusteringSampler(Strategy):

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.cluster_assignment: Optional[np.ndarray] = None

    def get_embeddings_and_margins(self, idxs: np.ndarray):
        out = self.collect_scores(idxs, "embed_margin",
                                  keys=("embedding", "margin"))
        return out["embedding"], out["margin"]

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        subset = self.cfg.subset_unlabeled
        if subset is None:
            idxs_for_hac = self.available_query_idxs(shuffle=False)
        else:
            idxs_for_hac = np.sort(
                self.available_query_idxs(shuffle=True)[:subset])
        if len(idxs_for_hac) == 0:
            return idxs_for_hac, 0

        need_clustering = self.cluster_assignment is None or subset is not None
        if need_clustering:
            embeddings, margins = self.get_embeddings_and_margins(
                idxs_for_hac)
            from sklearn.cluster import AgglomerativeClustering
            n_clusters = min(N_CLUSTERS, len(idxs_for_hac))
            assignment = AgglomerativeClustering(
                n_clusters=n_clusters).fit(embeddings).labels_.copy()
        else:
            # Cached-assignment rounds only need fresh margins — skip the
            # [N, D] embedding haul entirely.
            margins = self.collect_scores(idxs_for_hac, "prob_stats",
                                          keys=("margin",))["margin"]
            assignment = self.cluster_assignment

        cluster_ids, cluster_count = np.unique(assignment,
                                               return_counts=True)
        # Smallest clusters first; ties by id (:64-66).
        order = sorted(zip(cluster_count.tolist(), cluster_ids.tolist()))
        cluster_ids_sorted = [cid for _, cid in order]

        budget = int(min(len(idxs_for_hac), budget))
        query_idxs = []
        start_cluster = 0
        while len(query_idxs) < budget:
            # Round-robin: one min-margin pick per remaining cluster, small
            # clusters first; a cluster that empties advances the start
            # pointer (:71-87).
            for i in range(start_cluster, len(cluster_ids_sorted)):
                cid = cluster_ids_sorted[i]
                members = np.flatnonzero(assignment == cid)
                pick = members[np.argmin(margins[members])]
                assignment[pick] = -1
                query_idxs.append(int(idxs_for_hac[pick]))
                if len(members) == 1:
                    start_cluster += 1
                if len(query_idxs) >= budget:
                    break

        # Carry forward assignments of the still-unqueried examples (:89).
        self.cluster_assignment = assignment[assignment != -1]
        self.logger.info(f"Number of queried images: {budget}")
        return np.asarray(query_idxs, dtype=np.int64), budget
