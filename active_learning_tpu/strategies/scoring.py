"""Sharded acquisition-scoring passes over the unlabeled pool.

The reference scores the pool single-process on one GPU inside each
sampler's ``query`` (e.g. src/query_strategies/margin_sampler.py:19-45,
confidence_sampler.py:8-47, mase_sampler.py:30-96): a DataLoader walk with a
per-batch forward, hauling full softmax/embedding tensors back to host.

Here scoring is a first-class, mesh-parallel primitive: one jitted step per
(model, view, statistic) computes the per-example statistics on device over
a batch whose leading axis is sharded across the mesh's data axis, and only
the tiny per-example results (a few floats each) return to host.  This is
the "distributed acquisition scoring" row of SURVEY.md §2's parallelism
table — the big TPU win the reference lacks.

Every step function has signature ``step(variables, batch) -> dict`` where
each dict value has leading batch axis, and every batch row carries its pool
index and a validity mask (data/pipeline.py), so padding never contaminates
scores.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.augment import apply_view
from ..data.core import Dataset, ViewSpec
from ..parallel import mesh as mesh_lib
from ..pool import bucket_size
from ..data.pipeline import (batch_index_lists, iterate_batches,
                             padded_batch_layout)

# Registered step-builders (scripts/al_lint.py recompile-hazard): every
# jax.jit in this module sits inside one of these factories (one step
# per (model, view), reused across rounds) or is the module-level
# head_pair_norms; a stray jit outside them fails the lint.
_STEP_BUILDERS = ("make_prob_stats_step", "make_embed_step",
                  "make_badge_step", "make_mase_step", "head_pair_norms")


def batched_min_dist_update(factors, sqn: jnp.ndarray,
                            min_dist: jnp.ndarray,
                            center_idxs: jnp.ndarray) -> jnp.ndarray:
    """One batched k-center distance fold: min_dist <- min(min_dist,
    min_c ||g_. - g_c||^2) over the q centers in ``center_idxs``, in a
    single [N, q] pass over the factor matrices.

    This is the selection hot path's per-step min-reduce, and it lives
    here with the other mesh-parallel scoring primitives because its
    operands follow the pool-axis layout collect_pool produces: with the
    pool axis sharded over the mesh's data axis the [shard, q] distance
    strip, its min over q, and the running-min update are all
    shard-local — the batched greedy step's only cross-shard reduction
    is the subsequent masked top-k, ONE collective per q picks instead
    of one per pick (strategies/kcenter.py wires the sharding).
    """
    from .kcenter import dots_to_many

    d = (sqn[:, None] + sqn[center_idxs][None, :]
         - 2.0 * dots_to_many(factors, center_idxs))
    return jnp.minimum(min_dist, jnp.min(d, axis=1))


# Bucket floor for the ring column feed's center-id plan: labeled sets
# grow round over round, so the padded length rides the pool bucket
# ladder — round N+1 reuses round N's ring executables until the
# labeled count crosses a bucket boundary.
RING_CENTER_FLOOR = 1024


def ring_center_layout(center_idxs: np.ndarray, sentinel: int,
                       ndev: int, floor: int = RING_CENTER_FLOOR
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """The ring column feed's center-block plan (DESIGN.md §15) — the
    column analogue of ``chunk_row_slices``: the [L] global labeled-
    center ids padded up to a ``pool.bucket_size`` ladder length
    rounded to divide the mesh, id-padded with ``sentinel`` (an index
    no shard owns, so ``mesh_lib.owner_rows`` returns exact zeros for
    it) and masked via the returned validity vector.  Shard i of the
    ring starts with the contiguous slice ``[i*L/ndev, (i+1)*L/ndev)``
    of this layout; after ndev ring hops every shard has folded every
    valid center exactly once.  Host index math only — never a factor
    byte (the whole point: the ring feed replaced the host column-block
    uploads)."""
    idxs = np.asarray(center_idxs, dtype=np.int32)
    l_pad = bucket_size(max(1, len(idxs)), floor=floor)
    l_pad += (-l_pad) % max(1, int(ndev))
    cidx = np.full(l_pad, int(sentinel), dtype=np.int32)
    cidx[:len(idxs)] = idxs
    cvalid = np.zeros(l_pad, dtype=np.float32)
    cvalid[:len(idxs)] = 1.0
    return cidx, cvalid


def make_prob_stats_step(model, view: ViewSpec) -> Callable:
    """Per-example softmax statistics in one fused pass: top-1 probability
    (ConfidenceSampler's score, confidence_sampler.py:33-36), top1-top2
    probability margin (MarginSampler's score, margin_sampler.py:33-35),
    the predictive entropy (served by /v1/score — no reference sampler
    uses it, but it rides the same softmax for free), and the predicted
    label.  This step is shared verbatim by the offline samplers and the
    scoring service (serve/executor.py), which is what makes a served
    score bit-for-bit the offline score at the same batch shape."""

    @jax.jit
    def step(variables, batch):
        x = apply_view(batch["image"], view, train=False)
        logits = model.apply(variables, x, train=False)
        logits32 = logits.astype(jnp.float32)
        probs = jax.nn.softmax(logits32, axis=-1)
        logp = jax.nn.log_softmax(logits32, axis=-1)
        top2, top2_idx = jax.lax.top_k(probs, 2)
        return {
            "confidence": top2[:, 0],
            "margin": top2[:, 0] - top2[:, 1],
            # -sum p log p via log_softmax; a prob that underflowed to
            # exactly 0 would make 0 * -inf = NaN, so those entries are
            # pinned to the limit value 0.
            "entropy": -jnp.sum(jnp.where(probs > 0, probs * logp, 0.0),
                                axis=-1),
            "pred": top2_idx[:, 0].astype(jnp.int32),
        }

    return step


def make_embed_step(model, view: ViewSpec, with_probs: bool = False
                    ) -> Callable:
    """Final-embedding extraction (the reference's
    ``return_features='finalembed'`` pass, coreset_sampler.py:43-58), with
    optional softmax margin for MarginClusteringSampler
    (margin_clustering_sampler.py:23-45)."""

    @jax.jit
    def step(variables, batch):
        x = apply_view(batch["image"], view, train=False)
        logits, embedding = model.apply(variables, x, train=False,
                                        return_features=True)
        out = {"embedding": embedding}
        if with_probs:
            probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            top2, _ = jax.lax.top_k(probs, 2)
            out["margin"] = top2[:, 0] - top2[:, 1]
            out["pred"] = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return out

    return step


def make_badge_step(model, view: ViewSpec, pool_512: bool = False
                    ) -> Callable:
    """BADGE gradient-embedding FACTORS (badge_sampler.py:22-48).

    The gradient of CE(logits, argmax logits) w.r.t. the logits is
    closed-form — softmax(z) - onehot(argmax z) — so no autograd pass is
    needed (the reference runs torch.autograd.grad per batch,
    badge_sampler.py:36-37).  The full gradient embedding is the rank-1
    outer product a (x) e; we return the two factors instead of the [C*D]
    flattened product (see strategies/kcenter.py for why that is exact).

    ``pool_512``: the PartitionedBADGE variant pools the (C, D) grad
    embedding with adaptive average pooling to
    (min(16, C), 512 // min(16, C)) — 16x32=512 dims for ImageNet, 10x51
    for CIFAR, exactly the reference's ``pool_h = min(POOLING_H, C)`` rule
    (badge_sampler.py:9-10,41-44).  Pooling a rank-1 matrix factor-wise is
    exact, so each factor is pooled by its own averaging matrix.
    """
    from .kcenter import adaptive_avg_pool_matrix

    @jax.jit
    def step(variables, batch):
        x = apply_view(batch["image"], view, train=False)
        logits, embedding = model.apply(variables, x, train=False,
                                        return_features=True)
        logits = logits.astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        pred = jnp.argmax(logits, axis=-1)
        a = probs - jax.nn.one_hot(pred, logits.shape[-1],
                                   dtype=jnp.float32)
        e = embedding
        if pool_512:
            c, d = a.shape[1], e.shape[1]
            pool_h = min(16, c)
            pool_w = int(512 / pool_h)
            a = a @ jnp.asarray(adaptive_avg_pool_matrix(c, pool_h))
            e = e @ jnp.asarray(adaptive_avg_pool_matrix(d, pool_w))
        return {"grad_a": a, "grad_e": e}

    return step


@jax.jit
def head_pair_norms(kernel: jnp.ndarray) -> jnp.ndarray:
    """[C, C] table of ||w_c - w_j|| over the head rows, by explicit row
    differences (peak live [C, D]).  Batch-independent: callers that score
    many batches against one head compute this once per head (see
    make_mase_step) — NOT via the Gram identity G_cc + G_jj - 2 G_cj,
    whose float32 cancellation would misreport near-duplicate head columns
    as coincident (denominator 0 -> radius +inf)."""
    w = kernel.T.astype(jnp.float32)  # [C, D]
    return jax.lax.map(
        lambda wc: jnp.linalg.norm(w - wc[None, :], axis=-1), w)


def boundary_radii(embedding: jnp.ndarray, kernel: jnp.ndarray,
                   bias: jnp.ndarray,
                   pair_norms: Optional[jnp.ndarray] = None
                   ) -> Dict[str, jnp.ndarray]:
    """Closed-form distance from each embedding to every one-vs-one decision
    boundary of the linear head (MASE, mase_sampler.py:59-79).

    For predicted class c and any class j, the boundary is the hyperplane
    {e : (w_c - w_j)·e + (b_c - b_j) = 0}; the L2 distance from e is
    ((w_c - w_j)·e + b_c - b_j) / ||w_c - w_j||.  The j == c entry is 0/0
    and mapped to +inf, matching the reference's nan -> inf fix-up.

    The full [B, C, D] boundary tensor the reference materializes per
    batch (mase_sampler.py:62-70 — 2 GB at B=256, C=1000, D=2048) never
    exists here, WITHOUT giving up its float32 exactness:

      numerator   e·(w_c - w_j) + (b_c - b_j), with the weight DIFFERENCE
                  formed first — the algebraically equal logit difference
                  logit_c - logit_j subtracts two large rounded dot
                  products and quantizes away small margins between
                  near-duplicate head columns.  Computed in class blocks
                  (a lax.map over [B, block, D] tiles) so peak memory is
                  bounded while every entry matches the reference's
                  full-tensor einsum;
      denominator ||w_c - w_j||, the batch-independent ``head_pair_norms``
                  table — pass it as ``pair_norms`` when scoring many
                  batches against one head so the C-step map runs once per
                  head, not once per batch.

    kernel is the Flax Dense kernel [D, C]; bias [C].
    """
    e = embedding.astype(jnp.float32)  # [B, D]
    w = kernel.T.astype(jnp.float32)  # [C, D]
    b = bias.astype(jnp.float32)  # [C]
    logits = e @ w.T + b  # [B, C]
    preds = jnp.argmax(logits, axis=-1)  # [B]
    if pair_norms is None:
        pair_norms = head_pair_norms(kernel)  # [C, C]
    denom = pair_norms[preds]  # [B, C]

    c, d = w.shape
    block = min(c, max(1, 2 ** 25 // max(1, e.shape[0] * d)))  # ~128MB tile
    pad = (-c) % block
    w_pad = jnp.pad(w, ((0, pad), (0, 0)))
    b_pad = jnp.pad(b, (0, pad))
    w_pred, b_pred = w[preds], b[preds]  # [B, D], [B]

    def numer_block(args):
        wb, bb = args  # [block, D], [block]
        delta = w_pred[:, None, :] - wb[None, :, :]  # [B, block, D]
        return (jnp.einsum("bd,bkd->bk", e, delta)
                + b_pred[:, None] - bb[None, :])

    numer = jax.lax.map(numer_block,
                        (w_pad.reshape(-1, block, d),
                         b_pad.reshape(-1, block)))  # [nb, B, block]
    numer = jnp.moveaxis(numer, 0, 1).reshape(e.shape[0], c + pad)[:, :c]
    radii = jnp.where(denom > 0, numer / jnp.maximum(denom, 1e-30), jnp.inf)
    return {"radii": radii, "pred": preds.astype(jnp.int32)}


def make_mase_step(model, view: ViewSpec) -> Callable:
    """Per-class boundary radii + min margin, fully on device.

    The reference materializes [B, C, D] tensors per batch on GPU
    (mase_sampler.py:62-79); ``boundary_radii`` reduces both terms
    algebraically so the largest intermediate is [C, D], and the
    batch-independent pair-norm table is computed once per HEAD (a pool
    scan runs thousands of batches against one set of weights) via a
    one-slot cache keyed on the kernel array's identity.
    """
    cache: Dict[str, Any] = {}

    @jax.jit
    def jitted_step(variables, batch, pair_norms):
        x = apply_view(batch["image"], view, train=False)
        _, embedding = model.apply(variables, x, train=False,
                                   return_features=True)
        kernel = variables["params"]["linear"]["kernel"]
        bias = variables["params"]["linear"]["bias"]
        out = boundary_radii(embedding, kernel, bias, pair_norms=pair_norms)
        out["min_margin"] = jnp.min(out["radii"], axis=-1)
        return out

    def step(variables, batch):
        kernel = variables["params"]["linear"]["kernel"]
        if isinstance(kernel, jax.core.Tracer):
            # Called under someone else's trace (the resident-pool gather
            # runner, parallel/resident.py): a host-side cache can't help
            # there, so inline the norms into that computation.  Resident
            # pools are in-memory/CIFAR-scale, where the C-step map is
            # trivial; the C=1000 disk datasets always take the host path
            # below.
            return jitted_step(variables, batch, None)
        # Identity (not equality) check; holding the reference keeps the
        # id from being reused by a different array.
        if cache.get("kernel") is not kernel:
            cache["kernel"] = kernel
            cache["norms"] = head_pair_norms(kernel)
        return jitted_step(variables, batch, cache["norms"])

    return step


# In-memory pools up to this size stay resident on device across ALL
# rounds and samplers (uint8, replicated like the trainer's epoch-scan
# arrays; the per-batch gather output is what gets data-sharded).  This
# constant is only the DIRECT-CALLER default: production callers pass
# the trainer's resolved budget, which auto-sizes from live HBM headroom
# when TrainConfig.resident_scoring_bytes is None
# (parallel/resident.resolve_budget).  The shared pool cache + jitted
# gather-runners live in parallel/resident.py so scoring and evaluation
# upload each pool exactly once between them.
from ..config import RESIDENT_SCORING_BYTES_DEFAULT as RESIDENT_MAX_BYTES
from ..parallel import resident as resident_lib


# -- chunk-resumable scoring (the pipelined round) --------------------------
#
# A scoring pass over (idxs, batch_size) is a SEQUENCE of fixed-shape
# batches, and each jitted step call is independent of its neighbors, so
# the pass can be cut at any batch boundary and resumed — or computed
# out of order, on another thread, from a different-but-equal variables
# tree — without changing a single output bit: collect_pool(idxs[sl])
# over a batch-aligned row slice produces exactly the batches sl covers
# of the monolithic collect_pool(idxs) call (same rows per batch, same
# tail padding, same jitted executable).  The speculative scorer of the
# pipelined round (experiment/pipeline.py) leans on this: it pre-scores
# chunk slices while training still runs, and any chunk invalidated by
# a later best checkpoint is recomputed inline at query time; splicing
# the chunks back together is bit-identical to the sequential pass
# (pinned in tests/test_pipeline.py).

def chunk_row_slices(n_rows: int, batch_size: int,
                     chunk_batches: int) -> List[slice]:
    """Row slices covering ``chunk_batches`` whole batches each (the last
    takes the remainder) — the chunk plan both the speculative scorer
    and the inline-completion path iterate, so the two can never
    disagree on chunk boundaries."""
    from ..data.pipeline import num_batches
    if n_rows <= 0:
        return []
    n_b = num_batches(n_rows, batch_size)
    step = max(1, int(chunk_batches))
    return [slice(b0 * batch_size, min((b0 + step) * batch_size, n_rows))
            for b0 in range(0, n_b, step)]


def splice_chunks(chunks: List[Dict[str, np.ndarray]]
                  ) -> Dict[str, np.ndarray]:
    """Concatenate per-chunk host outputs (in chunk order) back into one
    idxs-aligned dict — the inverse of scoring each chunk_row_slices
    entry separately."""
    if len(chunks) == 1:
        return chunks[0]
    return {k: np.concatenate([c[k] for c in chunks], axis=0)
            for k in chunks[0]}


class _NullGate:
    """The no-lock stand-in for mesh_lib.DispatchGate when collect_pool
    runs single-threaded (every caller outside the pipelined round):
    context enter/exit and drain are all no-ops."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def drain(self, tree):
        return tree


_NULL_GATE = _NullGate()


def _finalize(chunks: Dict[str, list], multi: bool, mesh, n: int
              ) -> Dict[str, np.ndarray]:
    if multi:
        return {k: np.asarray(mesh_lib.fetch(jnp.concatenate(v, axis=0),
                                             mesh))[:n]
                for k, v in chunks.items()}
    return {k: np.concatenate(v, axis=0)[:n] for k, v in chunks.items()}


def collect_pool(
    dataset: Dataset,
    idxs: np.ndarray,
    batch_size: int,
    step_fn: Callable,
    variables,
    mesh,
    num_workers: int = 0,
    prefetch: int = 2,
    keys: Optional[Iterable[str]] = None,
    resident_cache: Optional[Dict] = None,
    resident_max_bytes: int = RESIDENT_MAX_BYTES,
    host_s2d: bool = False,
    pool_sharding: str = "replicated",
    dispatch_lock: Optional[Any] = None,
) -> Dict[str, np.ndarray]:
    """Run ``step_fn`` over ``dataset[idxs]`` in fixed-shape sharded batches
    and return host arrays of length ``len(idxs)``, row i scoring pool index
    ``idxs[i]``.  Alignment is *enforced*: the per-batch index rows carried
    by the pipeline (data/pipeline.py) are collected alongside the scores
    and checked against ``idxs`` — the class of bug the reference has at
    confidence_sampler.py:41 (sorting by a scrambled score vector) cannot
    happen silently here.

    This is the engine behind every sampler's scoring pass — the TPU
    replacement for the reference's per-sampler DataLoader loops.

    ``idxs`` must be non-empty (samplers guard the exhausted-pool case
    before scoring).

    ``dispatch_lock``: a mesh_lib.DispatchGate held around every jitted
    dispatch (never around a host fetch).  The pipelined round's
    speculative scorer and the trainer share one gate
    (Trainer.dispatch_lock) so two threads' collective-bearing
    computations always enqueue in ONE global order on every device —
    and on CPU meshes the gate's drain_mode additionally completes each
    computation before release (XLA:CPU reorders execution behind the
    enqueue order; see DispatchGate).  None (every single-threaded
    caller) costs nothing.
    """
    idxs = np.asarray(idxs)
    if dispatch_lock is None:
        dispatch_lock = _NULL_GATE
    n = len(idxs)
    if n == 0:
        raise ValueError("collect_pool called with empty idxs; guard the "
                         "exhausted-pool case in the sampler")
    # Telemetry: chunk-granular spans + heartbeat ticks over the pool
    # scan (experiment → round → phase → collect_pool chunk in the
    # trace).  A chunk is the streaming path's flush unit (FETCH_EVERY
    # batches); both objects are inert no-ops unless a run installed
    # telemetry.
    from ..telemetry import runtime as tele_runtime
    from ..telemetry import spans as tele_spans
    tracer = tele_spans.get_tracer()
    tele = tele_runtime.get_run()
    t_pool0 = time.perf_counter()
    # Bulk-fetch cadence of the streaming path AND the chunk-span/tick
    # granularity of both paths (single-process: keep per-batch outputs
    # ON DEVICE and fetch every FETCH_EVERY batches — a per-batch
    # np.asarray is a blocking round-trip that serializes the whole
    # pipeline on a remote/tunneled runtime, measured 10x+ end-to-end;
    # deferred fetches let async dispatch overlap decode, h2d, and
    # compute, bounding extra HBM to ~FETCH_EVERY batches of outputs).
    FETCH_EVERY = 32

    def chunk_span(t0: float, first_batch: int, n_batches: int,
                   rows: int) -> None:
        tracer.complete(
            "collect_pool_chunk", t0, time.perf_counter(),
            args={"batches": n_batches, "first_batch": first_batch,
                  "rows": rows})
    # Device-resident fast path for in-memory pools: upload once per
    # experiment (the caller owns ``resident_cache``), then every batch of
    # every round's every sampler is an on-device gather — zero image
    # bytes cross the host<->device boundary after the first round.  A
    # pool that is ALREADY uploaded keeps its fast path even if a budget
    # refresh shrank the budget below its size (resident_lib.cached).
    # ``pool_sharding`` "row": the upload is row-sharded (rows/ndev per
    # chip) and the runner assembles each batch from the shard owners —
    # scores stay bit-identical (tests/test_pool_sharding.py); the
    # runner follows the ENTRY's actual layout either way.
    shard_ways = (mesh.devices.size
                  if pool_sharding == "row" and mesh is not None else 1)
    if (resident_cache is not None
            and resident_lib.eligible(dataset, resident_max_bytes,
                                      cache=resident_cache,
                                      shard_ways=shard_ways)):
        images_dev, _ = resident_lib.pool_arrays(resident_cache, dataset,
                                                 mesh,
                                                 sharding=pool_sharding)
        run = resident_lib.get_runner(
            resident_cache, step_fn, mesh,
            sharded=mesh_lib.is_row_sharded(images_dev))
        multi = mesh_lib.is_multiprocess(mesh)
        chunks: Dict[str, list] = {}
        t_chunk, chunk_first = t_pool0, 0
        for i, b in enumerate(batch_index_lists(idxs, batch_size)):
            ids, mask = padded_batch_layout(b, batch_size)
            with dispatch_lock:
                small = mesh_lib.replicate((ids.astype(np.int32), mask),
                                           mesh)
                out = run(variables, images_dev, *small)
                dispatch_lock.drain(out)
            if keys is not None:
                out = {k: out[k] for k in keys}
            for k, v in out.items():
                # Keep DEVICE arrays: a per-batch np.asarray would block on
                # each batch and stall async dispatch (the host path hides
                # that sync behind its threaded decode; here there is no
                # host work to overlap).  One fetch at the end.
                chunks.setdefault(k, []).append(v)
            if (i + 1) % FETCH_EVERY == 0:
                tele.tick(step=i + 1)
                chunk_span(t_chunk, chunk_first, i + 1 - chunk_first,
                           min((i + 1) * batch_size, n))
                t_chunk, chunk_first = time.perf_counter(), i + 1
        if i + 1 > chunk_first:
            chunk_span(t_chunk, chunk_first, i + 1 - chunk_first, n)
        tracer.complete("collect_pool", t_pool0, time.perf_counter(),
                        args={"rows": n, "path": "resident"})
        if multi:
            return _finalize(chunks, True, mesh, n)
        return {k: np.asarray(jnp.concatenate(v, axis=0))[:n]
                for k, v in chunks.items()}
    # On a multi-host mesh each process gathers/decodes only its own rows
    # of every global batch; score rows come back in GLOBAL batch order
    # (mesh_lib.fetch all-gathers sharded outputs), so the global row
    # layout is recomputed here both to check alignment and to map scores
    # back to pool indices.
    local = mesh_lib.process_local_rows(mesh, batch_size)
    multi = mesh_lib.is_multiprocess(mesh)
    layouts = [padded_batch_layout(b, batch_size)[0]
               for b in batch_index_lists(idxs, batch_size)]
    chunks: Dict[str, list] = {}
    pending: Dict[str, list] = {}

    def flush():
        for k, v in pending.items():
            if v:
                merged = v[0] if len(v) == 1 else jnp.concatenate(v, axis=0)
                chunks.setdefault(k, []).append(np.asarray(merged))
                v.clear()

    def checked_host_batches():
        for i, batch in enumerate(iterate_batches(
                dataset, idxs, batch_size, num_threads=num_workers,
                prefetch=prefetch, local=local, s2d=host_s2d)):
            # The threaded prefetcher must deliver batches in order, and
            # this process's rows must be exactly its slice of the global
            # layout — the class of bug the reference has at
            # confidence_sampler.py:41 (scores sorted by a scrambled
            # index) cannot pass silently here.
            if not np.array_equal(batch["index"],
                                  layouts[i][local].astype(np.int32)):
                raise AssertionError(
                    "scoring rows misaligned with the global batch layout")
            yield batch

    # Async double-buffered host->device feed (data/cache.device_prefetch):
    # the gather/decode AND the h2d dispatch of batch n+1 overlap batch
    # n's device compute, so a pool too big for residency is bounded by
    # max(host feed, PCIe, device) instead of their sum — the fallback
    # leg of the pool-residency default.
    from ..data.cache import device_prefetch
    t_chunk, chunk_first = time.perf_counter(), 0
    i = -1
    for i, sharded in enumerate(device_prefetch(
            checked_host_batches(),
            lambda b: mesh_lib.shard_batch(b, mesh))):
        with dispatch_lock:
            out = step_fn(variables, sharded)
            dispatch_lock.drain(out)
        if keys is not None:
            out = {k: out[k] for k in keys}
        for k, v in out.items():
            # Multi-host: keep device arrays and cross-host-gather ONCE
            # after the loop — a per-batch gather would serialize a DCN
            # round-trip into every step of the acquisition hot path.
            (chunks if multi else pending).setdefault(k, []).append(v)
        if (i + 1) % FETCH_EVERY == 0:
            if not multi:
                # Periodic flush (device concat -> ONE host fetch ->
                # buffers freed): bounds the extra HBM to ~FETCH_EVERY
                # batches of outputs even for [B, D] embedding passes.
                flush()
            tele.tick(step=i + 1)
            chunk_span(t_chunk, chunk_first, i + 1 - chunk_first,
                       min((i + 1) * batch_size, n))
            t_chunk, chunk_first = time.perf_counter(), i + 1
    if not multi:
        flush()
    if i + 1 > chunk_first:
        chunk_span(t_chunk, chunk_first, i + 1 - chunk_first, n)
    tracer.complete("collect_pool", t_pool0, time.perf_counter(),
                    args={"rows": n, "path": "stream"})
    return _finalize(chunks, multi, mesh, n)
