"""VAAL: Variational Adversarial Active Learning (arXiv:1904.00370).

Reference: src/query_strategies/vaal_sampler.py:15-280.  A VAE and a latent
discriminator co-train alongside the classifier; acquisition picks the
points the discriminator scores most-likely-unlabeled.

Per training batch, three updates (vaal_train, :185-274):
  1. classifier SGD step on the labeled batch (shared with the base
     Trainer);
  2. VAE step: recon+KLD on the labeled batch, the same transductively on
     an unlabeled batch, plus ``adversary_param`` x BCE pushing the
     discriminator to call BOTH batches labeled;
  3. discriminator step on freshly-encoded (post-update) latents: labeled
     -> 1, unlabeled -> 0.

TPU design: steps 2+3 are ONE jitted function over the sharded batch pair
(the heavy compute is the VAE convs — mesh data parallelism comes from the
batch sharding like every other step); the classifier step and all
validation / early-stopping / checkpoint bookkeeping are reused from
Trainer.fit via its ``batch_hook`` seam instead of re-implementing the
whole epoch loop (the reference copies ~100 lines of parallel_train_fn).

Reference quirks preserved:
  * one crop window shared by every VAE forward of a step (the per-batch
    np.random seed, :214, vae.py:62-78);
  * the discriminator step re-encodes with the JUST-updated VAE, in train
    mode, so BN stats advance on those forwards too (:251-253);
  * the KL term is SUMMED over batch and latent dims while the recon MSE
    is a mean (vae_loss, :276-280);
  * both aux optimizers are Adam but follow the classifier's epoch LR
    schedule shape (:139-144).

Divergence (documented): the reference hard-maps num_classes 10/1000 to a
latent scale and rejects anything else (:23-29); here the VAE crop adapts
to the image size (64 for >=64px inputs, else the full image — any size
divisible by 16), which reproduces both reference cases exactly.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from ..data.augment import apply_view
from ..data.pipeline import iterate_batches
from ..models.vaal import VAE, Discriminator, crop_size_for, random_crop
from ..parallel import mesh as mesh_lib
from ..train.optim import make_lr_schedule
from . import scoring
from .base import Strategy, register_strategy

# Registered step-builders (scripts/al_lint.py recompile-hazard): both
# jitted steps are built once per sampler and reused across epochs.
_STEP_BUILDERS = ("_build_vaal_step", "_build_score_step")

# Donating callables stored on attributes (al_lint donation-safety):
# the co-training step donates the VAALState at position 0 — every call
# site must rebind self.vaal_state from the result in the same
# statement or the lint flags a use-after-donate.
_DONATES = {"_vaal_step": (0,)}


class VAALState(struct.PyTreeNode):
    vae_params: dict
    vae_stats: dict
    vae_opt: tuple
    d_params: dict
    d_opt: tuple


def _masked_mse(recon, x, mask):
    per_row = jnp.mean((recon - x) ** 2, axis=(1, 2, 3))
    return jnp.sum(per_row * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def _masked_kld(mu, logvar, mask):
    # Reference sums over batch AND latent dims (vaal_sampler.py:278-279).
    per_row = -0.5 * jnp.sum(1 + logvar - mu ** 2 - jnp.exp(logvar), axis=1)
    return jnp.sum(per_row * mask)


def _masked_bce(preds, target, mask):
    p = jnp.clip(preds.reshape(-1), 1e-7, 1 - 1e-7)
    per = -(target * jnp.log(p) + (1.0 - target) * jnp.log(1.0 - p))
    return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)


@register_strategy("VAALSampler")
class VAALSampler(Strategy):

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        vcfg = self.cfg.vaal
        hw = self.al_set.image_shape[0]
        self.crop = crop_size_for(hw)
        if self.crop % 16 != 0:
            raise ValueError(
                f"VAAL needs an input crop divisible by 16, got {self.crop}")
        self.vae = VAE(z_dim=vcfg.vae_latent_dim, nc=3, crop=self.crop)
        self.disc = Discriminator(z_dim=vcfg.vae_latent_dim)
        self.adversary_param = float(vcfg.adversary_param)
        self.lr_vae_at = make_lr_schedule(self.train_cfg.scheduler,
                                          vcfg.lr_vae)
        self.lr_d_at = make_lr_schedule(self.train_cfg.scheduler,
                                        vcfg.lr_discriminator)
        self._tx_vae = optax.scale_by_adam()
        self._tx_d = optax.scale_by_adam()
        self.vaal_state: VAALState = None
        self._vaal_step = self._build_vaal_step()
        self._score_step = self._build_score_step()

    # -- state ------------------------------------------------------------

    def _init_vaal_state(self, key: jax.Array) -> VAALState:
        k_vae, k_d = jax.random.split(key)
        x = jnp.zeros((2, self.crop, self.crop, 3), jnp.float32)
        vae_vars = self.vae.init(k_vae, x, train=False)
        d_params = self.disc.init(
            k_d, jnp.zeros((2, self.cfg.vaal.vae_latent_dim)))["params"]
        state = VAALState(
            vae_params=vae_vars["params"],
            vae_stats=vae_vars["batch_stats"],
            vae_opt=self._tx_vae.init(vae_vars["params"]),
            d_params=d_params,
            d_opt=self._tx_d.init(d_params))
        return mesh_lib.replicate(state, self.mesh)

    def init_network_weights(self) -> None:
        """Classifier re-init + fresh VAE/discriminator every round
        (vaal_sampler.py:72-75)."""
        super().init_network_weights()
        self._init_key, sub = jax.random.split(self._init_key)
        self.vaal_state = self._init_vaal_state(sub)

    # -- round-level resume (the reference gets this via whole-object
    # pickle, resume_training.py:38-52; here the seam is explicit) --------

    def aux_state_bytes(self):
        if self.vaal_state is None:
            return None
        from flax import serialization
        return serialization.to_bytes(
            jax.tree.map(np.asarray, self.vaal_state))

    def restore_aux_state(self, data: bytes) -> None:
        from flax import serialization
        # Template with the right treedef/shapes; its values are fully
        # overwritten.  PRNGKey(0) here does NOT touch _init_key, so the
        # restored key stream continues exactly as the uninterrupted run.
        template = jax.tree.map(np.asarray,
                                self._init_vaal_state(jax.random.PRNGKey(0)))
        restored = serialization.from_bytes(template, data)
        self.vaal_state = mesh_lib.replicate(restored, self.mesh)

    # -- the jitted co-training step --------------------------------------

    def _build_vaal_step(self):
        vae, disc = self.vae, self.disc
        tx_vae, tx_d = self._tx_vae, self._tx_d
        adversary = self.adversary_param
        view = self.train_set.view
        crop = self.crop

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(vs: VAALState, batch_l, batch_u, key, lr_vae, lr_d):
            ks = jax.random.split(key, 7)
            x_l = apply_view(batch_l["image"], view, key=ks[0], train=True)
            x_u = apply_view(batch_u["image"], view, key=ks[1], train=True)
            # Same window for labeled AND unlabeled (see module docstring).
            x_l = random_crop(x_l, crop, ks[2])
            x_u = random_crop(x_u, crop, ks[2])
            m_l, m_u = batch_l["mask"], batch_u["mask"]

            def vae_loss_fn(vae_params):
                v = {"params": vae_params, "batch_stats": vs.vae_stats}
                (recon_l, _, mu_l, lv_l), mut = vae.apply(
                    v, x_l, ks[3], train=True, mutable=["batch_stats"])
                v = {"params": vae_params,
                     "batch_stats": mut["batch_stats"]}
                (recon_u, _, mu_u, lv_u), mut = vae.apply(
                    v, x_u, ks[4], train=True, mutable=["batch_stats"])
                unsup = _masked_mse(recon_l, x_l, m_l) + _masked_kld(
                    mu_l, lv_l, m_l)
                trans = _masked_mse(recon_u, x_u, m_u) + _masked_kld(
                    mu_u, lv_u, m_u)
                d_l = disc.apply({"params": vs.d_params}, mu_l)
                d_u = disc.apply({"params": vs.d_params}, mu_u)
                adv = _masked_bce(d_l, 1.0, m_l) + _masked_bce(d_u, 1.0, m_u)
                return unsup + trans + adversary * adv, mut["batch_stats"]

            (vae_loss, vae_stats), grads = jax.value_and_grad(
                vae_loss_fn, has_aux=True)(vs.vae_params)
            upd, vae_opt = tx_vae.update(grads, vs.vae_opt, vs.vae_params)
            vae_params = optax.apply_updates(
                vs.vae_params, jax.tree.map(lambda u: -lr_vae * u, upd))

            # Discriminator step on post-update latents, train-mode
            # forwards (BN stats advance — reference :251-253).
            v = {"params": vae_params, "batch_stats": vae_stats}
            (_, _, mu_l, _), mut = vae.apply(v, x_l, ks[5], train=True,
                                             mutable=["batch_stats"])
            v = {"params": vae_params, "batch_stats": mut["batch_stats"]}
            (_, _, mu_u, _), mut = vae.apply(v, x_u, ks[6], train=True,
                                             mutable=["batch_stats"])
            mu_l = jax.lax.stop_gradient(mu_l)
            mu_u = jax.lax.stop_gradient(mu_u)

            def d_loss_fn(d_params):
                d_l = disc.apply({"params": d_params}, mu_l)
                d_u = disc.apply({"params": d_params}, mu_u)
                return (_masked_bce(d_l, 1.0, m_l)
                        + _masked_bce(d_u, 0.0, m_u))

            d_loss, d_grads = jax.value_and_grad(d_loss_fn)(vs.d_params)
            upd, d_opt = tx_d.update(d_grads, vs.d_opt, vs.d_params)
            d_params = optax.apply_updates(
                vs.d_params, jax.tree.map(lambda u: -lr_d * u, upd))

            new_state = VAALState(vae_params=vae_params,
                                  vae_stats=mut["batch_stats"],
                                  vae_opt=vae_opt, d_params=d_params,
                                  d_opt=d_opt)
            return new_state, {"vae_loss": vae_loss, "d_loss": d_loss}

        return step

    # -- training ---------------------------------------------------------

    def train(self) -> None:
        """Trainer.fit drives the classifier exactly as the base Strategy;
        the batch hook runs the VAE+discriminator co-step on each labeled
        batch paired with a cycling unlabeled batch
        (vaal_train, vaal_sampler.py:185-274)."""
        if self.state is None:
            self.init_network_weights()
        if self.vaal_state is None:
            self._init_key, sub = jax.random.split(self._init_key)
            self.vaal_state = self._init_vaal_state(sub)
        labeled = self.already_labeled_idxs()
        bs = self.trainer.padded_batch_size(
            self.train_cfg.loader_tr.batch_size)
        hook_key = jax.random.PRNGKey(int(self.rng.integers(2 ** 31)))

        unlabeled_iter_holder = {"iter": None}

        def next_unlabeled_batch():
            it = unlabeled_iter_holder["iter"]
            batch = next(it, None) if it is not None else None
            if batch is None:
                unlabeled = self.available_query_idxs(shuffle=True)
                if len(unlabeled) == 0:  # pool exhausted: recycle labeled
                    unlabeled = labeled
                unlabeled_iter_holder["iter"] = iterate_batches(
                    self.train_set, unlabeled, bs,
                    local=mesh_lib.process_local_rows(self.mesh, bs))
                batch = next(unlabeled_iter_holder["iter"])
            return batch

        def metric_cb(name: str, value: float, step: int) -> None:
            self.sink.log_metric(name, value, step=step)

        def batch_hook(epoch: int, sharded_batch: Dict) -> None:
            nonlocal hook_key
            batch_u = next_unlabeled_batch()
            hook_key, sub = jax.random.split(hook_key)
            lr_vae = jnp.float32(self.lr_vae_at(epoch - 1))
            lr_d = jnp.float32(self.lr_d_at(epoch - 1))
            self.vaal_state, _ = self._vaal_step(
                self.vaal_state, sharded_batch,
                mesh_lib.shard_batch(batch_u, self.mesh),
                sub, lr_vae, lr_d)

        self.logger.info(f"Starting training on round {self.round}")
        result = self.trainer.fit(
            self.state, self.train_set, labeled, self.al_set,
            self.pool.eval_idxs, n_epoch=self.cfg.n_epoch,
            es_patience=self.cfg.early_stop_patience, rng=self.rng,
            round_idx=self.round, weight_paths=self.weight_paths(),
            metric_cb=metric_cb, batch_hook=batch_hook)
        self.state = result.state
        self.best_epoch = result.best_epoch
        self.logger.info(f"Finished training on round {self.round}")

    # -- acquisition ------------------------------------------------------

    def _build_score_step(self):
        vae, disc = self.vae, self.disc
        view = self.al_set.view
        crop = self.crop
        crop_key = jax.random.PRNGKey(0)  # deterministic window at scoring

        @jax.jit
        def step(variables, batch):
            x = apply_view(batch["image"], view, train=False)
            x = random_crop(x, crop, crop_key)
            v = {"params": variables["vae_params"],
                 "batch_stats": variables["vae_stats"]}
            _, _, mu, _ = vae.apply(v, x, None, train=False)
            preds = disc.apply({"params": variables["d_params"]}, mu)
            return {"d_score": preds.reshape(-1)}

        return step

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        """Lowest discriminator score first — the points the adversary is
        most confident are unlabeled (vaal_sampler.py:39-70)."""
        idxs = self.available_query_idxs(shuffle=False)
        if len(idxs) == 0:
            return idxs, 0
        if self.vaal_state is None:
            # Only reachable resuming a save that predates aux-state
            # persistence: score with a fresh adversary rather than crash,
            # but say so — this round's picks differ from an uninterrupted
            # run's.
            self.logger.warning(
                "VAAL aux state missing from the resumed experiment; "
                "initializing a fresh VAE/discriminator for this query")
            self._init_key, sub = jax.random.split(self._init_key)
            self.vaal_state = self._init_vaal_state(sub)
        variables = {"vae_params": self.vaal_state.vae_params,
                     "vae_stats": self.vaal_state.vae_stats,
                     "d_params": self.vaal_state.d_params}
        loader = self.train_cfg.loader_te
        resident_kwargs = self._resident_kwargs()
        # VAAL scores with its VAE/discriminator, not the classifier: the
        # VAE is 3-channel, so an s2d-stem classifier must not switch the
        # host feed to space-to-depth batches here.
        resident_kwargs["host_s2d"] = False
        out = scoring.collect_pool(
            self.al_set, idxs, self._score_batch_size(), self._score_step,
            variables, self.mesh, num_workers=loader.num_workers,
            prefetch=loader.prefetch, **resident_kwargs)
        budget = int(min(len(idxs), budget))
        order = np.argsort(out["d_score"], kind="stable")[:budget]
        self.logger.info(f"Number of queried images: {budget}")
        return idxs[order], budget
