"""Acquisition strategies: the Strategy engine + the reference's 13 samplers.

``get_strategy`` replaces the reference's eval()-based registry
(src/query_strategies/get_strategy.py:16-17) with an explicit one.
"""

from ..registry import STRATEGIES
from .base import Strategy, register_strategy

# Importing a sampler module registers its classes.
from . import random_sampler as _random_sampler  # noqa: F401
from . import uncertainty as _uncertainty  # noqa: F401
from . import mase as _mase  # noqa: F401
from . import coreset as _coreset  # noqa: F401
from . import clustering as _clustering  # noqa: F401
from . import balancing as _balancing  # noqa: F401
from . import vaal as _vaal  # noqa: F401


def get_strategy(name: str):
    return STRATEGIES.get(name)
