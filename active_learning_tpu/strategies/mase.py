"""MASE and BASE: decision-boundary-distance acquisition.

Reference: src/query_strategies/mase_sampler.py:6-96 (minimum distance to a
one-vs-one decision boundary of the linear head, in final-embedding space)
and base_sampler.py:6-41 (its class-balanced variant).

The closed-form radii are computed fully on device in one fused pass per
batch (strategies/scoring.boundary_radii); the reference's mathematical
self-check — perturbing an embedding by the optimal epsilon must land it on
the decision boundary (mase_sampler.py:85-90) — is a unit test here
(tests/test_samplers.py) instead of a runtime assert.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .base import Strategy, register_strategy


@register_strategy("MASESampler")
class MASESampler(Strategy):
    """Examples closest to ANY decision boundary first
    (mase_sampler.py:20-28)."""

    def speculative_scoring_plan(self):
        """Both MASE and BASE score the UNSHUFFLED available set (no
        rng), so the pipelined round pre-scores it; keys None = every
        output of the mase step (query reads margin, radii, AND pred)."""
        idxs = self.pool.available_query_idxs(shuffle=False)
        if len(idxs) == 0:
            return None
        return {"kind": "mase", "keys": None, "idxs": idxs}

    def compute_margins(self, idxs: np.ndarray):
        """(min_margins, per_class_radii, pred_labels) for ``idxs``
        (mase_sampler.py:30-96, vectorized + sharded)."""
        out = self.collect_scores(idxs, "mase")
        return out["min_margin"], out["radii"], out["pred"]

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        idxs = self.available_query_idxs(shuffle=False)
        if len(idxs) == 0:
            return idxs, 0
        min_margins, _, _ = self.compute_margins(idxs)
        budget = int(min(len(idxs), budget))
        order = np.argsort(min_margins, kind="stable")[:budget]
        return idxs[order], budget


@register_strategy("BASESampler")
class BASESampler(MASESampler):
    """Class-balanced MASE: per-(predicted)-class quota of
    ``budget/num_classes`` (+1 for the first ``budget % C`` classes), where
    a point's distance *for class c* is its min margin if it is predicted c,
    else its radius to the c-boundary (base_sampler.py:22-35)."""

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        idxs = self.available_query_idxs(shuffle=False)
        if len(idxs) == 0:
            return idxs, 0
        min_margins, radii, preds = self.compute_margins(idxs)
        budget = int(min(len(idxs), budget))

        taken = np.zeros(len(idxs), dtype=bool)
        selected = []
        for c in range(self.num_classes):
            quota = budget // self.num_classes + int(
                c < budget % self.num_classes)
            if quota == 0:
                continue
            dist = np.where(preds == c, min_margins, radii[:, c])
            dist = np.where(taken, np.inf, dist)
            picks = np.argsort(dist, kind="stable")[:quota]
            taken[picks] = True
            selected.extend(picks.tolist())
        assert len(selected) == len(set(selected))
        return idxs[np.asarray(selected, dtype=np.int64)], budget
