"""Device-resident greedy k-center (and k-means++-style randomized variant).

This is the sequential core of Coreset/BADGE acquisition.  The reference
materializes the full N x N squared-L2 matrix on GPU and, per selection
step, recomputes the min over all labeled columns
(src/query_strategies/coreset_sampler.py:59-105) — O(N^2) memory and
O(budget * N * L) work, with a host round-trip per step.

The TPU design keeps only the factor matrices and a length-N min-distance
vector on device and runs the whole selection on device — no N x N
matrix, no per-step host sync:

  * Embeddings are a tuple of FACTOR matrices.  Plain coreset is one factor
    X [N, D] with dot(i,j) = X_i . X_j.  BADGE's gradient embedding
    g_i = (softmax(z_i) - onehot(argmax z_i)) (x) e_i (badge_sampler.py:40)
    is rank-1, so it is stored as TWO factors (A [N, C], E [N, D]) with
    dot(i,j) = (A_i . A_j)(E_i . E_j) — the C*D-dim outer product is never
    materialized.  Adaptive average pooling of a rank-1 matrix is itself
    rank-1 (the mean over a bin rectangle of a_c * e_d is the product of
    the two bin means), so the pooled variant (badge_sampler.py:41-44)
    keeps the same factorized form.
  * Deterministic selection runs BATCHED: each step takes the top-q
    provisionally-farthest candidates, verifies them with an exact
    in-batch re-check (below), and folds all accepted picks into the
    min-distance vector with ONE [N, q] pass — the pool is read once per
    q picks instead of once per pick, and under a pool-sharded layout the
    strip min is shard-local so each step needs a single cross-shard
    reduction (see scoring.batched_min_dist_update).
  * The randomized (k-means++ D^2) mode stays one pick per step — a
    batched draw would change the sampling distribution.

**Batched farthest-first is exact.**  Let v_1 >= ... >= v_q be the top-q
current min-distances and T = v_q.  Candidate picks are accepted one at a
time in-batch: each sub-step recomputes the remaining candidates' exact
min-distances against the already-accepted picks (a [q, q] table — tiny)
and accepts the maximum iff it exceeds T strictly.  Every non-candidate's
distance only shrinks as picks accrue and started <= T, so an accepted
candidate dominates the whole pool — the pick sequence is identical to
q=1 greedy (pinned in tests/test_kcenter.py).  When the re-check fails
the step stops early; progress is still >= 1 pick (the first candidate is
the unbatched argmax).

**Backend.**  The XLA scans are the ONLY backend.  A fused Pallas
kernel existed through r5 behind a measured dispatcher; the on-MXU A/B
ran three times at 0.67x/1.11x/0.93x the XLA scan with
``pallas_picks_match: False`` every time, so it was deleted per the r5
verdict (wrong-on-hardware code behind an env var is a trap, not a
feature).  The decision record survives in DESIGN.md §5;
``LAST_BACKEND`` keeps the bench's backend attribution.

Pool shapes are padded to bounded-waste geometric buckets
(pool.bucket_size: 1/8-octave granularity — padded rows ride every
distance matmul, so the recurring compute waste stays bounded, 25%
worst-case) before the jitted scans, so subset-capped pools whose size
drifts across AL rounds reuse the previous round's executables; the
distance / selectable carries are donated, so each step updates them in
place.

Distances are SQUARED L2 throughout, matching the reference (it never
takes a sqrt; the randomized mode's selection probabilities are therefore
k-means++ D^2 weights, coreset_sampler.py:80-92).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ..parallel import mesh as mesh_lib
from ..pool import bucket_size

Factors = Tuple[jnp.ndarray, ...]

# Which scan answered the last kcenter_greedy call ("xla" sequential /
# "xla-batched"): bench.py's kcenter phases record it so a capture is
# attributable to its code path.
LAST_BACKEND: Optional[str] = None

# Which pool layout the last kcenter_greedy call selected over
# ("replicated" / "row") — the bench's pool_sharding attribution.
LAST_SHARDING: Optional[str] = None

# Whether the last kcenter_greedy call fed its initial-min/minimax
# column scans through the ring-permute feed (the row-sharded backend's
# only column feed since ISSUE 15) — the bench's ring_feed attribution
# on al_round lines.  None until a call runs; False on the replicated
# backend.
LAST_RING_FEED: Optional[bool] = None

# Each pick's squared distance-to-(labeled ∪ earlier picks) AT PICK
# TIME, host float32 aligned with the last kcenter_greedy call's return
# (NaN marks the once-per-experiment minimax/uniform seed, which has no
# labeled set to be distant from).  The values already exist inside the
# selection scans — the argmax/top-k maximum IS the pick's distance —
# so riding them out beside the picks costs no extra pool pass, no
# extra collective, and cannot perturb the pick sequence (pinned in
# tests/test_diagnostics.py).  The experiment-truth layer
# (telemetry/diagnostics.py) reads this for rd_pick_min_dist /
# rd_pick_mean_dist and the k-center drift histogram.
LAST_PICK_DISTS: Optional[np.ndarray] = None

# Default q for the batched deterministic greedy: the f32 sublane tile
# (8), the smallest batch that both cuts scan steps ~8x and fills an MXU
# strip.  Overridden per experiment via ExperimentConfig.kcenter_batch.
DEFAULT_BATCH_Q = 8

# Pools are padded to the enclosing geometric bucket (>= this floor) so
# the jitted scans compile once per BUCKET, not once per subset-capped
# pool size; padded rows are zero factors masked out via ``selectable``.
POOL_BUCKET_FLOOR = 256

# Registered step-builders (scripts/al_lint.py recompile-hazard): the
# module-level jitted scans compile once per pool bucket; the sharded
# backend's jits live inside _build_sharded_fns (one set per
# (mesh, n_factors), cached in _SHARDED_JITS).  A jax.jit anywhere else
# in this module fails the lint.
_STEP_BUILDERS = ("_min_dist_chunk", "_kcenter_scan",
                  "_kcenter_scan_batched", "_minimax_row",
                  "_build_sharded_fns")


def self_sq_norms(factors: Factors) -> jnp.ndarray:
    """||g_i||^2 = prod_F (F_i . F_i)  — [N]."""
    out = None
    for f in factors:
        s = jnp.sum(f * f, axis=1)
        out = s if out is None else out * s
    return out


def dots_to(factors: Factors, idx) -> jnp.ndarray:
    """g_. . g_idx = prod_F (F @ F_idx)  — [N]."""
    out = None
    for f in factors:
        d = f @ f[idx]
        out = d if out is None else out * d
    return out


def dots_to_many(factors: Factors, idxs) -> jnp.ndarray:
    """g_. . g_j for j in idxs — [N, K] (blocked initial-min helper)."""
    out = None
    for f in factors:
        d = f @ f[idxs].T
        out = d if out is None else out * d
    return out


def dots_between(factors: Factors, idxs) -> jnp.ndarray:
    """g_i . g_j for i, j in idxs — [K, K] (the batched re-check table)."""
    out = None
    for f in factors:
        rows = f[idxs]
        d = rows @ rows.T
        out = d if out is None else out * d
    return out


@functools.partial(jax.jit, donate_argnums=(3,))
def _min_dist_chunk(factors: Factors, sqn: jnp.ndarray, chunk: jnp.ndarray,
                    min_dist: jnp.ndarray) -> jnp.ndarray:
    d = sqn[:, None] + sqn[chunk][None, :] - 2.0 * dots_to_many(factors, chunk)
    return jnp.minimum(min_dist, jnp.min(d, axis=1))


def min_sq_dist_to(factors: Factors, sqn: jnp.ndarray,
                   labeled_idxs: np.ndarray,
                   chunk_size: int = 1024) -> jnp.ndarray:
    """min_j in labeled ||g_i - g_j||^2 for all i, blocked so the live
    [N, chunk] tile stays small (the O(N^2) escape the reference lacks)."""
    n = sqn.shape[0]
    min_dist = jnp.full((n,), jnp.inf, dtype=jnp.float32)
    labeled_idxs = np.asarray(labeled_idxs)
    for start in range(0, len(labeled_idxs), chunk_size):
        chunk = labeled_idxs[start:start + chunk_size]
        if len(chunk) < chunk_size:  # pad with repeats: min is unaffected
            chunk = np.concatenate(
                [chunk, np.repeat(chunk[:1], chunk_size - len(chunk))])
        min_dist = _min_dist_chunk(factors, sqn, jnp.asarray(chunk), min_dist)
    return min_dist


@functools.partial(jax.jit, static_argnames=("budget", "randomize"),
                   donate_argnums=(2, 3))
def _kcenter_scan(factors: Factors, sqn: jnp.ndarray, min_dist: jnp.ndarray,
                  selectable: jnp.ndarray, budget: int, randomize: bool,
                  key: jax.Array) -> jnp.ndarray:
    """The q=1 greedy loop as one scan (randomized mode, and the batched
    path's degenerate case).  ``selectable`` is 1.0 on unlabeled rows;
    labeled rows have min_dist ~ 0 so the deterministic argmax never
    picks them (mirroring the reference, which also relies on that)."""

    def step(carry, key):
        min_dist, selectable = carry
        if randomize:
            # k-means++ D^2 draw over unlabeled rows; if every unlabeled
            # distance is 0 the reference degenerates to a uniform draw via
            # its +=1e-5 retry loop (coreset_sampler.py:83-92).
            p = jnp.clip(min_dist, 0.0, None) * selectable
            total = jnp.sum(p)
            weights = jnp.where(total > 0, p, selectable)
            idx = jax.random.categorical(key, jnp.log(weights))
            # The pick's distance diagnostic is the draw's own weight
            # (clipped min-dist) — already materialized for the draw.
            dval = p[idx]
        else:
            # The reference relies on picked rows having min_dist == 0 to
            # avoid re-selection; under float32 the incremental update can
            # leave a tiny positive residual on dense pools, so mask
            # explicitly — same selection, no duplicate risk.
            masked = jnp.where(selectable > 0, min_dist, -jnp.inf)
            idx = jnp.argmax(masked)
            dval = masked[idx]
        d_new = sqn + sqn[idx] - 2.0 * dots_to(factors, idx)
        min_dist = jnp.minimum(min_dist, d_new)
        selectable = selectable.at[idx].set(0.0)
        return (min_dist, selectable), (idx, dval)

    keys = jax.random.split(key, budget)
    _, (picks, dists) = jax.lax.scan(step, (min_dist, selectable), keys)
    return picks, dists


def _recheck_candidates(cands: jnp.ndarray, vals: jnp.ndarray,
                        d_cc: jnp.ndarray, limit: jnp.ndarray,
                        sentinel: int):
    """Exact in-batch acceptance over the top-q candidates (see module
    docstring).  ``cands``/``vals`` come from top_k of the masked
    min-distances (descending, ties lowest-index first — matching
    argmax); ``d_cc`` is the [q, q] candidate pairwise distance table;
    ``limit`` caps accepted picks (budget remainder).  Returns
    (order [q] of candidate POSITIONS in acceptance order, n_acc,
    dvals [q] — each accepted pick's exact min-distance at acceptance,
    in acceptance order; an accepted candidate dominates the whole pool
    so this IS its distance-to-(labeled ∪ earlier picks), the number
    the experiment-truth diagnostics ride out)."""
    q = cands.shape[0]
    thresh = vals[q - 1]

    def body(_, st):
        cur, accepted, order, dvals, n_acc, last, stop = st
        cur = jnp.minimum(cur, d_cc[:, last])
        avail = jnp.where(accepted, -jnp.inf, cur)
        m = jnp.max(avail)
        # Lowest POOL index among in-batch maxima: the q=1 argmax's
        # tie-break, so batched picks replay the sequential order.
        p = jnp.argmin(jnp.where(avail >= m, cands, sentinel))
        # Strict > T: at == T a non-candidate could tie and win the q=1
        # argmax by index — stop and let the next step re-rank the pool.
        ok = (m > thresh) & (~stop) & (n_acc < limit)
        accepted = accepted.at[p].set(accepted[p] | ok)
        order = jnp.where(ok, order.at[n_acc].set(p.astype(jnp.int32)),
                          order)
        dvals = jnp.where(ok, dvals.at[n_acc].set(m), dvals)
        last = jnp.where(ok, p, last)
        n_acc = n_acc + ok.astype(jnp.int32)
        return (cur, accepted, order, dvals, n_acc, last, stop | ~ok)

    init = (vals, jnp.zeros(q, bool).at[0].set(True),
            jnp.zeros(q, jnp.int32),
            jnp.zeros(q, vals.dtype).at[0].set(vals[0]), jnp.int32(1),
            jnp.int32(0), jnp.asarray(False))
    _, _, order, dvals, n_acc, _, _ = jax.lax.fori_loop(0, q - 1, body,
                                                        init)
    return order, n_acc, dvals


def _accept_pick_batch(masked: jnp.ndarray, q: int, limit, sentinel: int,
                       pair_dists):
    """One batched-greedy candidate round, factored out of the scan body:
    masked top-q, exact in-batch re-check, and the padded accepted sequence
    (unaccepted slots repeat the first pick — the min-fold is a no-op for
    duplicates and the next step overwrites their pick slots).
    ``pair_dists(cands) -> [q, q]`` supplies the candidate pairwise
    squared distances in whichever factor layout the caller holds.
    Returns (seq [q] pool indices, dseq [q] acceptance-time distances,
    n_acc) — dseq slots past n_acc are dead exactly like seq's repeated
    first pick (the next step overwrites their pick slots)."""
    vals, cands = jax.lax.top_k(masked, q)
    order, n_acc, dseq = _recheck_candidates(cands, vals,
                                             pair_dists(cands), limit,
                                             sentinel)
    slot = jnp.arange(q)
    seq = jnp.where(slot < n_acc, cands[order], cands[order[0]])
    return seq, dseq, n_acc


@functools.partial(jax.jit, static_argnames=("budget", "q"),
                   donate_argnums=(2, 3))
def _kcenter_scan_batched(factors: Factors, sqn: jnp.ndarray,
                          min_dist: jnp.ndarray, selectable: jnp.ndarray,
                          budget: int, q: int) -> jnp.ndarray:
    """Batched deterministic greedy: top-q candidates, exact re-check,
    one fused [N, q] distance pass per accepted batch.  Pick-for-pick
    identical to the q=1 scan; ~q x fewer pool reads."""
    from . import scoring

    n = sqn.shape[0]
    # q trailing slots absorb the final step's padded writes; sliced off.
    picks0 = jnp.zeros(budget + q, jnp.int32)
    dists0 = jnp.zeros(budget + q, min_dist.dtype)

    def cond(st):
        return st[4] < budget

    def pair_dists(cands):
        return (sqn[cands][:, None] + sqn[cands][None, :]
                - 2.0 * dots_between(factors, cands))

    def body(st):
        min_dist, selectable, picks, dists, count = st
        masked = jnp.where(selectable > 0, min_dist, -jnp.inf)
        seq, dseq, n_acc = _accept_pick_batch(
            masked, q, jnp.minimum(q, budget - count), n, pair_dists)
        min_dist = scoring.batched_min_dist_update(factors, sqn, min_dist,
                                                   seq)
        selectable = selectable.at[seq].set(0.0)
        picks = jax.lax.dynamic_update_slice(picks, seq.astype(jnp.int32),
                                             (count,))
        dists = jax.lax.dynamic_update_slice(dists, dseq, (count,))
        return (min_dist, selectable, picks, dists, count + n_acc)

    _, _, picks, dists, _ = jax.lax.while_loop(
        cond, body, (min_dist, selectable, picks0, dists0, jnp.int32(0)))
    return picks[:budget], dists[:budget]


@functools.partial(jax.jit, static_argnames=("block",))
def _minimax_row(factors: Factors, sqn: jnp.ndarray, block: int = 2048
                 ) -> jnp.ndarray:
    """argmin_i max_j ||g_i - g_j||^2 — the reference's deterministic seed
    when nothing is labeled (coreset_sampler.py:96-100), computed with a
    blocked scan instead of the full N x N matrix."""
    n = sqn.shape[0]
    pad = (-n) % block
    order = jnp.arange(n + pad) % n

    def body(row_max, cols):
        d = sqn[:, None] + sqn[cols][None, :] - 2.0 * dots_to_many(
            factors, cols)
        return jnp.maximum(row_max, jnp.max(d, axis=1)), None

    row_max, _ = jax.lax.scan(body, jnp.full((n,), -jnp.inf),
                              order.reshape(-1, block))
    return jnp.argmin(row_max)


# -- the row-sharded backend (DESIGN.md §2b) -----------------------------
#
# The factor matrix is the selection scan's resident state (1.28M x 2048
# f32 = 10.5 GB for the full ImageNet pool) and used to be replicated
# per chip, so kcenter_select_maxn could only FIND the single-chip
# ceiling.  Here the pool axis is row-sharded over the mesh and every
# per-step pass runs shard-local inside shard_map, with exactly one
# family of collectives per step:
#
#   * distance strips / running-min updates: shard-local [rows/ndev, q];
#   * the farthest-point argmax / top-q: local reduce, then pmax + a
#     pmin index tie-break (lowest global index — the argmax rule), or
#     local top_k + an all_gather of ndev*q candidates (shard-major
#     order == global index order, so top_k's earliest-position
#     tie-break IS the replicated lowest-index tie-break);
#   * each accepted center's factor row: gathered FROM ITS OWNER by a
#     masked psum (non-owners contribute exact zeros — the sum is the
#     owner's row bit for bit), never by replicating the matrix.
#
# Every reduction is a min/max or a sum of exact zeros plus one value —
# no rounding anywhere — and each row's matvec stays on one shard, so
# the pick sequence is BIT-IDENTICAL to the replicated backend (pinned
# in tests/test_pool_sharding.py).  scripts/trace_lint.py check 6
# statically forbids these functions from full-pool host
# materialization (np.* / jax.device_get / .asarray) and from
# replicating the factor matrix (replicate / replicated_sharding).

# The functions trace_lint check 6 anchors on (renaming one away would
# silently drop the enforcement): the device tier may never touch np /
# host fetches at all; the orchestrator may do host index math but
# never device_get the pool or replicate a row-sharded array.
SHARDED_SELECTION_FNS = ("_build_sharded_fns", "_kcenter_greedy_sharded")

# Jitted sharded-selection programs, one set per (mesh, n_factors):
# AL round N+1 reuses round N's executables (shapes are bucketed the
# same way as the replicated path's — tests/test_compile_reuse.py).
_SHARDED_JITS: Dict = {}


def _build_sharded_fns(mesh, nf: int):
    """The jitted row-sharded selection programs for one mesh and factor
    count.  All bodies run inside shard_map over the data axis; factors
    and the per-row state vectors (sqn / min_dist / selectable /
    row_max) are sharded over pool rows, scalars and picks replicated."""
    axis = mesh_lib.DATA_AXIS
    ndev = mesh.devices.size
    fspec = tuple(P(axis, None) for _ in range(nf))
    vec, rep = P(axis), P()

    def _offset(rows: int, dtype=jnp.int32):
        return (jax.lax.axis_index(axis) * rows).astype(dtype)

    def _owned_or_oob(idxs, rows: int):
        """Global pick indices -> local positions on the owning shard,
        everything else mapped PAST the shard (rows) so scatter
        mode="drop" discards it.  A bare ``idxs - offset`` would go
        NEGATIVE on shards past the owner, and negative scatter indices
        wrap python-style BEFORE the drop check — silently zeroing the
        wrong rows (the bug this helper exists to prevent)."""
        off = _offset(rows, idxs.dtype)
        return jnp.where((idxs >= off) & (idxs < off + rows),
                         idxs - off, rows)

    def _take(factors, sqn, idxs):
        """Factor rows + self-norms for global ``idxs`` [K], gathered
        from their owning shards by masked psum (exact: zeros + the
        owner's value — mesh_lib.owner_rows, the one spelling of the
        idiom shared with resident.sharded_pool_gather)."""
        taken = tuple(mesh_lib.owner_rows(f, idxs, axis)
                      for f in factors)
        tsqn = mesh_lib.owner_rows(sqn, idxs, axis)
        return taken, tsqn

    def _argmax_global(vals, n_total: int):
        """Replicated global (argmax index, max value), ties to the
        LOWEST global index — the full-vector argmax rule, via pmax +
        pmin.  The max value rides out for free (it is the picked row's
        min-distance, the diagnostics layer's number) — no extra
        collective."""
        m_loc = jnp.max(vals)
        m = jax.lax.pmax(m_loc, axis)
        cand = jnp.where(m_loc >= m,
                         jnp.argmax(vals).astype(jnp.int32)
                         + _offset(vals.shape[0]),
                         jnp.int32(n_total))
        return jax.lax.pmin(cand, axis), m

    def _topk_global(vals, q: int):
        """Replicated global (values, indices) top-q.  Local top_k per
        shard, then top_k over the all_gathered ndev*q candidates —
        shard-major gather order is global-index order, so equal values
        resolve to the lowest global index exactly like the replicated
        top_k."""
        v, ix = jax.lax.top_k(vals, q)
        gi = ix.astype(jnp.int32) + _offset(vals.shape[0])
        av = jax.lax.all_gather(v, axis)
        ai = jax.lax.all_gather(gi, axis)
        v2, pos = jax.lax.top_k(av.reshape(-1), q)
        return v2, ai.reshape(-1)[pos]

    def _strip_min(factors, sqn, crows, csqn, min_dist):
        """Shard-local [rows/ndev, K] distance strip against K gathered
        center rows, folded into the running min — the sharded
        batched_min_dist_update."""
        d = None
        for f, r in zip(factors, crows):
            dd = f @ r.T
            d = dd if d is None else d * dd
        d = sqn[:, None] + csqn[None, :] - 2.0 * d
        return jnp.minimum(min_dist, jnp.min(d, axis=1))

    def _ring_min_body(factors, sqn, cidx, cvalid, min_dist):
        # The ring column feed's initial-min fold (DESIGN.md §15): the
        # [L] global labeled-center ids arrive replicated
        # (scoring.ring_center_layout — host index math, never a factor
        # byte); each shard owner-gathers ITS contiguous L/ndev slice
        # of center rows ONCE (mesh_lib.owner_rows — batch-sized,
        # exact), then the blocks rotate around the ring
        # (mesh_lib.ring_shift), each hop folding one shard-local
        # [rows/ndev, L/ndev] distance strip into the running min.  No
        # host column-block uploads, no replicated broadcast; min folds
        # are exact, so the result is bit-identical to the replicated
        # chunk scan.  Pad ids (sentinel, owned by nobody) gather as
        # zero rows and their columns mask to +inf.  The starting
        # blocks are seeded by masked PSUM-SCATTER (owner_rows'
        # reduce-scatter twin): every shard passes the same replicated
        # cidx, contributes the center rows it owns, and receives ITS
        # L/ndev slice of the assembled result — 1/ndev the wire of a
        # full owner_rows broadcast.  (A per-shard-different id slice
        # through owner_rows would cross-sum different gathers — the
        # bug class owner_rows_scattered exists to prevent.)
        lb = cidx.shape[0] // ndev
        me = jax.lax.axis_index(axis)
        vb = jax.lax.dynamic_slice_in_dim(cvalid, me * lb, lb, 0)
        crows = tuple(mesh_lib.owner_rows_scattered(f, cidx, axis)
                      for f in factors)
        csqn = mesh_lib.owner_rows_scattered(sqn, cidx, axis)

        def hop(_, carry):
            min_dist, crows, csqn, vb = carry
            d = None
            for f, r in zip(factors, crows):
                dd = f @ r.T
                d = dd if d is None else d * dd
            d = sqn[:, None] + csqn[None, :] - 2.0 * d
            d = jnp.where(vb[None, :] > 0, d, jnp.inf)
            min_dist = jnp.minimum(min_dist, jnp.min(d, axis=1))
            crows, csqn, vb = mesh_lib.ring_shift((crows, csqn, vb),
                                                  ndev, axis)
            return (min_dist, crows, csqn, vb)

        min_dist, _, _, _ = jax.lax.fori_loop(
            0, ndev, hop, (min_dist, crows, csqn, vb))
        return min_dist

    def _ring_minimax_body(factors, sqn, valid):
        # The minimax seed's all-pairs row-max over the SAME ring feed:
        # each shard's own factor block (with its sqn + validity)
        # rotates around the ring, folding a shard-local
        # [rows/ndev, rows/ndev] strip max per hop — after ndev hops
        # every real column has been seen exactly once.  Pad rows mask
        # to -inf as COLUMNS here (they must not lower any row's max);
        # as ROWS they are masked to +inf by _argmin_body.  Max folds
        # are exact, so the seed is the replicated seed.
        rows = sqn.shape[0]
        row_max0 = jnp.full((rows,), -jnp.inf)

        def hop(_, carry):
            row_max, block, bsqn, bvalid = carry
            d = None
            for f, bf in zip(factors, block):
                dd = f @ bf.T
                d = dd if d is None else d * dd
            d = sqn[:, None] + bsqn[None, :] - 2.0 * d
            d = jnp.where(bvalid[None, :] > 0, d, -jnp.inf)
            row_max = jnp.maximum(row_max, jnp.max(d, axis=1))
            block, bsqn, bvalid = mesh_lib.ring_shift(
                (block, bsqn, bvalid), ndev, axis)
            return (row_max, block, bsqn, bvalid)

        row_max, _, _, _ = jax.lax.fori_loop(
            0, ndev, hop, (row_max0, factors, sqn, valid))
        return row_max

    def _argmin_body(row_max, valid):
        # Pad rows (valid 0) forced to +inf so they can never win the
        # minimax seed's argmin; ties to the lowest global index.
        rm = jnp.where(valid > 0, row_max, jnp.inf)
        m_loc = jnp.min(rm)
        m = jax.lax.pmin(m_loc, axis)
        n_total = ndev * rm.shape[0]
        cand = jnp.where(m_loc <= m,
                         jnp.argmin(rm).astype(jnp.int32)
                         + _offset(rm.shape[0]),
                         jnp.int32(n_total))
        return jax.lax.pmin(cand, axis)

    def _scan_body(factors, sqn, min_dist, selectable, key, budget: int,
                   randomize: bool):
        n_total = sqn.shape[0] * ndev

        def step(carry, key):
            min_dist, selectable = carry
            if randomize:
                # The D^2 draw needs the full weight vector; all_gather
                # the O(N) scores (NOT the [N, D] factors) so the
                # categorical consumes the exact global vector the
                # replicated scan does — same bits, same draw.
                p = jnp.clip(min_dist, 0.0, None) * selectable
                p_all = jax.lax.all_gather(p, axis, tiled=True)
                sel_all = jax.lax.all_gather(selectable, axis, tiled=True)
                total = jnp.sum(p_all)
                weights = jnp.where(total > 0, p_all, sel_all)
                idx = jax.random.categorical(
                    key, jnp.log(weights)).astype(jnp.int32)
                # The already-gathered weight vector holds the pick's
                # clipped min-dist — the replicated scan's diagnostic,
                # same bits, zero extra collectives.
                dval = p_all[idx]
            else:
                masked = jnp.where(selectable > 0, min_dist, -jnp.inf)
                idx, dval = _argmax_global(masked, n_total)
            crows, csqn = _take(factors, sqn, idx[None])
            d = None
            for f, r in zip(factors, crows):
                dd = f @ r[0]  # matvec, like the replicated dots_to
                d = dd if d is None else d * dd
            min_dist = jnp.minimum(min_dist, sqn + csqn[0] - 2.0 * d)
            selectable = selectable.at[_owned_or_oob(idx, sqn.shape[0])
                                       ].set(0.0, mode="drop")
            return (min_dist, selectable), (idx, dval)

        keys = jax.random.split(key, budget)
        _, (picks, dists) = jax.lax.scan(step, (min_dist, selectable),
                                         keys)
        return picks, dists

    def _scan_batched_body(factors, sqn, min_dist, selectable, budget: int,
                           q: int):
        n_total = sqn.shape[0] * ndev
        picks0 = jnp.zeros(budget + q, jnp.int32)
        dists0 = jnp.zeros(budget + q, min_dist.dtype)

        def cond(st):
            return st[4] < budget

        def body(st):
            min_dist, selectable, picks, dists, count = st
            masked = jnp.where(selectable > 0, min_dist, -jnp.inf)
            vals, cands = _topk_global(masked, q)
            crows, csqn = _take(factors, sqn, cands)
            d_cc = None
            for r in crows:
                dd = r @ r.T
                d_cc = dd if d_cc is None else d_cc * dd
            d_cc = csqn[:, None] + csqn[None, :] - 2.0 * d_cc
            order, n_acc, dseq = _recheck_candidates(
                cands, vals, d_cc, jnp.minimum(q, budget - count), n_total)
            slot = jnp.arange(q)
            seq = jnp.where(slot < n_acc, cands[order], cands[order[0]])
            srows, ssqn = _take(factors, sqn, seq)
            min_dist = _strip_min(factors, sqn, srows, ssqn, min_dist)
            selectable = selectable.at[_owned_or_oob(seq, sqn.shape[0])
                                       ].set(0.0, mode="drop")
            picks = jax.lax.dynamic_update_slice(picks, seq, (count,))
            dists = jax.lax.dynamic_update_slice(dists, dseq, (count,))
            return (min_dist, selectable, picks, dists, count + n_acc)

        _, _, picks, dists, _ = jax.lax.while_loop(
            cond, body, (min_dist, selectable, picks0, dists0,
                         jnp.int32(0)))
        return picks[:budget], dists[:budget]

    # No donate_argnums on the sharded jits: the would-be-donated
    # carries are the O(N) min-dist/selectable vectors (KBs-to-MBs,
    # never the factor matrix), and XLA:CPU rejects donation of sharded
    # buffers with a per-call warning — not worth the log spam.
    @functools.partial(jax.jit, static_argnames=("budget", "q"))
    def scan_batched(factors, sqn, min_dist, selectable, budget, q):
        return shard_map(
            lambda f, s, md, sel: _scan_batched_body(f, s, md, sel,
                                                     budget, q),
            mesh=mesh, in_specs=(fspec, vec, vec, vec),
            out_specs=(rep, rep),
            check_rep=False)(factors, sqn, min_dist, selectable)

    @functools.partial(jax.jit, static_argnames=("budget", "randomize"))
    def scan_q1(factors, sqn, min_dist, selectable, key, budget, randomize):
        return shard_map(
            lambda f, s, md, sel, k: _scan_body(f, s, md, sel, k, budget,
                                                randomize),
            mesh=mesh, in_specs=(fspec, vec, vec, vec, rep),
            out_specs=(rep, rep), check_rep=False)(factors, sqn, min_dist,
                                                   selectable, key)

    @jax.jit
    def ring_min(factors, sqn, cidx, cvalid, min_dist):
        return shard_map(
            _ring_min_body, mesh=mesh,
            in_specs=(fspec, vec, rep, rep, vec), out_specs=vec,
            check_rep=False)(factors, sqn, cidx, cvalid, min_dist)

    @jax.jit
    def ring_minimax(factors, sqn, valid):
        return shard_map(
            _ring_minimax_body, mesh=mesh, in_specs=(fspec, vec, vec),
            out_specs=vec, check_rep=False)(factors, sqn, valid)

    @jax.jit
    def argmin_valid(row_max, valid):
        return shard_map(_argmin_body, mesh=mesh, in_specs=(vec, vec),
                         out_specs=rep, check_rep=False)(row_max, valid)

    return {"scan_batched": scan_batched, "scan_q1": scan_q1,
            "ring_min": ring_min, "ring_minimax": ring_minimax,
            "argmin_valid": argmin_valid}


def _record_picks(picks: np.ndarray, dists, n_seed: int) -> np.ndarray:
    """Publish the pick-distance diagnostics (LAST_PICK_DISTS) next to
    the picks being returned: seed slots get NaN (no labeled set to be
    distant from), the rest are the scan's pick-time min-distances.  The
    dists fetch rides the SAME already-computed executable output the
    picks fetch does — no extra pool pass, no effect on the picks."""
    global LAST_PICK_DISTS
    tail = (np.zeros(0, dtype=np.float32) if dists is None
            else np.asarray(dists, dtype=np.float32))
    LAST_PICK_DISTS = np.concatenate(
        [np.full(n_seed, np.nan, dtype=np.float32), tail])
    return picks


def _sharded_jits(mesh, nf: int) -> Dict:
    key = (mesh, nf)
    if key not in _SHARDED_JITS:
        _SHARDED_JITS[key] = _build_sharded_fns(mesh, nf)
    return _SHARDED_JITS[key]


def _kcenter_greedy_sharded(factors_np: Tuple[np.ndarray, ...],
                            labeled_mask: np.ndarray, budget: int,
                            randomize: bool, rng, q: int, key,
                            mesh) -> np.ndarray:
    """Row-sharded greedy k-center: the same selection as the replicated
    scans (bit-identical picks — see _build_sharded_fns), with per-chip
    residency of rows/ndev.  The factors arrive as HOST arrays and are
    uploaded per shard straight into the row sharding
    (mesh_lib.shard_rows) — the full matrix never materializes on any
    one device nor a second (padded) time on host (and on a
    multi-process mesh each host uploads only its own row range).  The
    initial min pass and the minimax seed feed their column blocks over
    the ring-permute feed (mesh_lib.ring_shift, DESIGN.md §15): blocks
    rotate device-to-device around the mesh instead of riding host
    uploads + replicated broadcast — the only host work left is the
    center-id layout (scoring.ring_center_layout, index math only)."""
    from . import scoring

    n = labeled_mask.shape[0]
    n_pad = bucket_size(n, floor=POOL_BUCKET_FLOOR)
    ndev = mesh.devices.size
    fns = _sharded_jits(mesh, len(factors_np))
    vec_sh = jax.sharding.NamedSharding(mesh, P(mesh_lib.DATA_AXIS))
    global LAST_RING_FEED
    LAST_RING_FEED = True

    # Per-shard upload straight into the row sharding (shard_rows with
    # rows=n_pad): the bucket pad materializes only on the tail shard's
    # block, so the matrix never holds a second, padded host copy — at
    # the 10.5 GB full-ImageNet scale that transient double would OOM
    # the very hosts the sharded pool targets.
    factors = tuple(mesh_lib.shard_rows(f, mesh, rows=n_pad)
                    for f in factors_np)
    # Row-wise self-norms: elementwise + a D-axis reduction, so the
    # eager dispatch stays row-sharded with no collectives, and each
    # row's bits match the replicated self_sq_norms.
    sqn = self_sq_norms(factors)

    labeled_idxs = np.flatnonzero(labeled_mask)
    picks_pre: list = []
    if len(labeled_idxs) == 0:
        if randomize:
            seed_idx = int(rng.integers(n))
        else:
            # Sharded minimax seed over the RING feed: each shard's own
            # factor block rotates around the mesh, folding a local
            # strip max per hop (_ring_minimax_body), then a global
            # argmin with pad rows masked to +inf.  max/min folds are
            # exact, so the seed is the replicated seed — with zero
            # host column uploads.
            valid = np.zeros(n_pad, np.float32)
            valid[:n] = 1.0
            valid_dev = jax.device_put(valid, vec_sh)
            row_max = fns["ring_minimax"](factors, sqn, valid_dev)
            seed_idx = int(fns["argmin_valid"](row_max, valid_dev))
        picks_pre.append(seed_idx)
        labeled_idxs = np.asarray([seed_idx])
        budget -= 1
    if budget <= 0:
        return _record_picks(np.asarray(picks_pre, dtype=np.int64),
                             None, len(picks_pre))
    q = max(1, min(q, budget))

    # Initial min pass over the RING column feed: the labeled-center
    # ids ride in replicated on a bucketed layout (index math only —
    # scoring.ring_center_layout), each shard owner-gathers its slice
    # of center rows once, and the blocks rotate around the mesh while
    # every shard folds [rows/ndev, L/ndev] strips into its running
    # min (_ring_min_body).  The pad sentinel n_pad is owned by no
    # shard, so pad columns gather as zeros and mask to +inf.
    cidx, cvalid = scoring.ring_center_layout(labeled_idxs, n_pad, ndev)
    min_dist = jax.device_put(np.full(n_pad, np.inf, np.float32), vec_sh)
    min_dist = fns["ring_min"](factors, sqn, jnp.asarray(cidx),
                               jnp.asarray(cvalid), min_dist)

    selectable = np.zeros(n_pad, dtype=np.float32)
    selectable[:n] = 1.0
    selectable[labeled_idxs] = 0.0
    sel_dev = jax.device_put(selectable, vec_sh)

    global LAST_BACKEND
    if q > 1:
        picks, dists = fns["scan_batched"](factors, sqn, min_dist,
                                           sel_dev, budget, q)
        LAST_BACKEND = "xla-batched"
    else:
        picks, dists = fns["scan_q1"](factors, sqn, min_dist, sel_dev,
                                      key, budget, bool(randomize))
        LAST_BACKEND = "xla"
    picks = np.asarray(picks, dtype=np.int64)
    return _record_picks(
        np.concatenate([np.asarray(picks_pre, dtype=np.int64), picks]),
        dists, len(picks_pre))


def row_capable(n: int, budget: int, mesh, batch_q: Optional[int] = None,
                randomize: bool = False) -> bool:
    """Whether ``kcenter_greedy`` would resolve a non-"replicated"
    ``pool_sharding`` to the row-sharded backend for this geometry:
    a single-process mesh with >1 device, the bucketed pool size
    dividing evenly over it, and at least one candidate batch of rows
    per shard.  This IS the gate ``kcenter_greedy`` applies — callers
    that must know the layout BEFORE paying for a selection (the
    ``kcenter_select_maxn`` bench climbs an ndev-times-larger pool on
    the row rungs) pre-check here instead of discovering a silent
    replicated fallback, at ndev times the per-chip bytes, after the
    run."""
    if mesh is None:
        return False
    ndev = mesh.devices.size
    budget = max(1, int(budget))
    q = 1 if randomize else int(batch_q or DEFAULT_BATCH_Q)
    q = max(1, min(q, budget))
    n_pad = bucket_size(n, floor=POOL_BUCKET_FLOOR)
    # Multi-process meshes qualify since the pod tier (DESIGN.md §15):
    # the collective backend's shard_map programs run identically over
    # DCN, and the factor upload assembles per process (shard_rows).
    return ndev > 1 and n_pad % ndev == 0 and n_pad // ndev >= q


def kcenter_greedy(
    factors: Sequence[np.ndarray],
    labeled_mask: np.ndarray,
    budget: int,
    randomize: bool = False,
    rng: Optional[np.random.Generator] = None,
    batch_q: Optional[int] = None,
    mesh=None,
    pool_sharding: Optional[str] = None,
) -> np.ndarray:
    """Select ``budget`` local row indices by greedy k-center over the
    factorized embeddings.  Matches coreset_sampler.coreset(:66-105):
    deterministic mode takes the farthest-point argmax (batched q picks
    per pool pass, pick-for-pick identical — see module docstring);
    randomized mode draws with D^2 probabilities one pick at a time.

    ``mesh`` + ``pool_sharding``: with a single-process multi-device
    mesh and pool_sharding "row" (or None/"auto"), the pool axis is
    ROW-SHARDED over the mesh's data axis and selection runs on the
    collective backend (_build_sharded_fns): distance strips and min
    folds shard-local, one argmax/top-q collective per step, center
    rows gathered from their owners — pick-for-pick identical to the
    replicated scans while each chip holds only rows/ndev of the factor
    matrix.  "replicated" forces the single-chip layout.  Returns
    selections in pick order."""
    labeled_mask = np.asarray(labeled_mask, dtype=bool)
    n = labeled_mask.shape[0]
    budget = int(budget)
    if budget <= 0:
        return _record_picks(np.zeros(0, dtype=np.int64), None, 0)
    if rng is None:
        rng = np.random.default_rng()
    key = jax.random.PRNGKey(int(rng.integers(2 ** 31)))
    q = 1 if randomize else int(batch_q or DEFAULT_BATCH_Q)
    q = max(1, min(q, budget))

    global LAST_SHARDING, LAST_RING_FEED
    use_row = (pool_sharding != "replicated"
               and row_capable(n, budget, mesh, batch_q=batch_q,
                               randomize=randomize))
    if use_row:
        LAST_SHARDING = "row"
        factors_np = tuple(np.asarray(f, dtype=np.float32)
                           for f in factors)
        return _kcenter_greedy_sharded(factors_np, labeled_mask, budget,
                                       randomize, rng, q, key, mesh)
    LAST_SHARDING = "replicated"
    LAST_RING_FEED = False

    factors = tuple(jnp.asarray(np.asarray(f), dtype=jnp.float32)
                    for f in factors)
    sqn = self_sq_norms(factors)
    labeled_idxs = np.flatnonzero(labeled_mask)
    picks_pre: list = []
    if len(labeled_idxs) == 0:
        # Seed point (coreset_sampler.py:95-100): uniform when randomized,
        # else the minimax row.
        if randomize:
            seed_idx = int(rng.integers(n))
        else:
            seed_idx = int(_minimax_row(factors, sqn))
        picks_pre.append(seed_idx)
        labeled_idxs = np.asarray([seed_idx])
        budget -= 1

    if budget <= 0:
        return _record_picks(np.asarray(picks_pre, dtype=np.int64),
                             None, len(picks_pre))

    q = max(1, min(q, budget))

    # Power-of-two pool bucketing: subset-capped pools drift in size
    # across AL rounds; padding to the enclosing bucket (zero factor
    # rows, selectable 0 — they can never win an argmax, a top-k
    # acceptance, or a D^2 draw) lets round N+1 reuse round N's compiled
    # executables instead of paying a fresh XLA compile.  Applied BEFORE
    # the initial min pass so the chunked _min_dist_chunk reuses too
    # (only the once-per-experiment minimax seed above runs unpadded — a
    # zero pad row could win ITS argmin).
    n_pad = bucket_size(n, floor=POOL_BUCKET_FLOOR)
    pad = n_pad - n
    if pad:
        factors = tuple(jnp.pad(f, ((0, pad), (0, 0))) for f in factors)
        sqn = jnp.pad(sqn, (0, pad))
    min_dist = min_sq_dist_to(factors, sqn, labeled_idxs)
    selectable = np.zeros(n_pad, dtype=np.float32)
    selectable[:n] = 1.0
    selectable[labeled_idxs] = 0.0

    global LAST_BACKEND
    sel_dev = jnp.asarray(selectable)
    if q > 1:
        picks, dists = _kcenter_scan_batched(factors, sqn, min_dist,
                                             sel_dev, budget, q)
        LAST_BACKEND = "xla-batched"
    else:
        picks, dists = _kcenter_scan(factors, sqn, min_dist, sel_dev,
                                     budget, bool(randomize), key)
        LAST_BACKEND = "xla"
    picks = np.asarray(picks, dtype=np.int64)
    return _record_picks(
        np.concatenate([np.asarray(picks_pre, dtype=np.int64), picks]),
        dists, len(picks_pre))


def adaptive_avg_pool_matrix(n_in: int, n_out: int) -> np.ndarray:
    """[n_in, n_out] averaging weights with torch adaptive_avg_pool bin
    edges: bin o covers [floor(o*In/Out), ceil((o+1)*In/Out)).  Pooling a
    vector is then ``v @ M`` (badge_sampler.py:41-44 applies the 2-D pool to
    the rank-1 grad embedding; pooling each factor separately is exact)."""
    m = np.zeros((n_in, n_out), dtype=np.float32)
    for o in range(n_out):
        start = int(np.floor(o * n_in / n_out))
        end = int(np.ceil((o + 1) * n_in / n_out))
        m[start:end, o] = 1.0 / (end - start)
    return m
