"""Device-resident greedy k-center (and k-means++-style randomized variant).

This is the sequential core of Coreset/BADGE acquisition.  The reference
materializes the full N x N squared-L2 matrix on GPU and, per selection
step, recomputes the min over all labeled columns
(src/query_strategies/coreset_sampler.py:59-105) — O(N^2) memory and
O(budget * N * L) work, with a host round-trip per step.

The TPU design keeps only the factor matrices and a length-N min-distance
vector on device and runs the whole selection as ONE ``lax.scan`` of
``budget`` steps — no N x N matrix, no per-step host sync:

  * Embeddings are a tuple of FACTOR matrices.  Plain coreset is one factor
    X [N, D] with dot(i,j) = X_i . X_j.  BADGE's gradient embedding
    g_i = (softmax(z_i) - onehot(argmax z_i)) (x) e_i (badge_sampler.py:40)
    is rank-1, so it is stored as TWO factors (A [N, C], E [N, D]) with
    dot(i,j) = (A_i . A_j)(E_i . E_j) — the C*D-dim outer product is never
    materialized.  Adaptive average pooling of a rank-1 matrix is itself
    rank-1 (the mean over a bin rectangle of a_c * e_d is the product of
    the two bin means), so the pooled variant (badge_sampler.py:41-44)
    keeps the same factorized form.
  * Each scan step does one fused [N, K] matvec per factor plus an
    argmax/categorical draw, then the incremental min-distance update
    min_dist <- min(min_dist, d(., new)) — equivalent to the reference's
    full recomputation because min over a growing set is associative.

Distances are SQUARED L2 throughout, matching the reference (it never
takes a sqrt; the randomized mode's selection probabilities are therefore
k-means++ D^2 weights, coreset_sampler.py:80-92).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Factors = Tuple[jnp.ndarray, ...]


def self_sq_norms(factors: Factors) -> jnp.ndarray:
    """||g_i||^2 = prod_F (F_i . F_i)  — [N]."""
    out = None
    for f in factors:
        s = jnp.sum(f * f, axis=1)
        out = s if out is None else out * s
    return out


def dots_to(factors: Factors, idx) -> jnp.ndarray:
    """g_. . g_idx = prod_F (F @ F_idx)  — [N]."""
    out = None
    for f in factors:
        d = f @ f[idx]
        out = d if out is None else out * d
    return out


def dots_to_many(factors: Factors, idxs) -> jnp.ndarray:
    """g_. . g_j for j in idxs — [N, K] (blocked initial-min helper)."""
    out = None
    for f in factors:
        d = f @ f[idxs].T
        out = d if out is None else out * d
    return out


@functools.partial(jax.jit, donate_argnums=(3,))
def _min_dist_chunk(factors: Factors, sqn: jnp.ndarray, chunk: jnp.ndarray,
                    min_dist: jnp.ndarray) -> jnp.ndarray:
    d = sqn[:, None] + sqn[chunk][None, :] - 2.0 * dots_to_many(factors, chunk)
    return jnp.minimum(min_dist, jnp.min(d, axis=1))


def min_sq_dist_to(factors: Factors, sqn: jnp.ndarray,
                   labeled_idxs: np.ndarray,
                   chunk_size: int = 1024) -> jnp.ndarray:
    """min_j in labeled ||g_i - g_j||^2 for all i, blocked so the live
    [N, chunk] tile stays small (the O(N^2) escape the reference lacks)."""
    n = sqn.shape[0]
    min_dist = jnp.full((n,), jnp.inf, dtype=jnp.float32)
    labeled_idxs = np.asarray(labeled_idxs)
    for start in range(0, len(labeled_idxs), chunk_size):
        chunk = labeled_idxs[start:start + chunk_size]
        if len(chunk) < chunk_size:  # pad with repeats: min is unaffected
            chunk = np.concatenate(
                [chunk, np.repeat(chunk[:1], chunk_size - len(chunk))])
        min_dist = _min_dist_chunk(factors, sqn, jnp.asarray(chunk), min_dist)
    return min_dist


@functools.partial(jax.jit, static_argnames=("budget", "randomize"))
def _kcenter_scan(factors: Factors, sqn: jnp.ndarray, min_dist: jnp.ndarray,
                  selectable: jnp.ndarray, budget: int, randomize: bool,
                  key: jax.Array) -> jnp.ndarray:
    """The greedy loop as one scan.  ``selectable`` is 1.0 on unlabeled
    rows; labeled rows have min_dist ~ 0 so the deterministic argmax never
    picks them (mirroring the reference, which also relies on that)."""

    def step(carry, key):
        min_dist, selectable = carry
        if randomize:
            # k-means++ D^2 draw over unlabeled rows; if every unlabeled
            # distance is 0 the reference degenerates to a uniform draw via
            # its +=1e-5 retry loop (coreset_sampler.py:83-92).
            p = jnp.clip(min_dist, 0.0, None) * selectable
            total = jnp.sum(p)
            weights = jnp.where(total > 0, p, selectable)
            idx = jax.random.categorical(key, jnp.log(weights))
        else:
            # The reference relies on picked rows having min_dist == 0 to
            # avoid re-selection; under float32 the incremental update can
            # leave a tiny positive residual on dense pools, so mask
            # explicitly — same selection, no duplicate risk.
            idx = jnp.argmax(jnp.where(selectable > 0, min_dist, -jnp.inf))
        d_new = sqn + sqn[idx] - 2.0 * dots_to(factors, idx)
        min_dist = jnp.minimum(min_dist, d_new)
        selectable = selectable.at[idx].set(0.0)
        return (min_dist, selectable), idx

    keys = jax.random.split(key, budget)
    _, picks = jax.lax.scan(step, (min_dist, selectable), keys)
    return picks


@functools.partial(jax.jit, static_argnames=("budget", "interpret"))
def _kcenter_scan_pallas(xt, sqn_row, min_dist_row, selectable, budget: int,
                         interpret: bool) -> jnp.ndarray:
    """Deterministic single-factor scan with the fused Pallas distance
    update (ops/kcenter_pallas.py): identical pick semantics to
    _kcenter_scan — argmax over the CURRENT min-distances, then one
    fused pass updates them against the pick.  Opt-in via
    AL_TPU_KCENTER_PALLAS (see kcenter_greedy)."""
    from ..ops import kcenter_pallas as kp

    def step(carry, _):
        min_dist_row, selectable = carry
        idx = jnp.argmax(jnp.where(selectable > 0, min_dist_row[0],
                                   -jnp.inf)).astype(jnp.int32)
        min_dist_row = kp.min_dist_update(xt, sqn_row, min_dist_row, idx,
                                          interpret=interpret)
        selectable = selectable.at[idx].set(0.0)
        return (min_dist_row, selectable), idx

    _, picks = jax.lax.scan(step, (min_dist_row, selectable), None,
                            length=budget)
    return picks


@functools.partial(jax.jit, static_argnames=("block",))
def _minimax_row(factors: Factors, sqn: jnp.ndarray, block: int = 2048
                 ) -> jnp.ndarray:
    """argmin_i max_j ||g_i - g_j||^2 — the reference's deterministic seed
    when nothing is labeled (coreset_sampler.py:96-100), computed with a
    blocked scan instead of the full N x N matrix."""
    n = sqn.shape[0]
    pad = (-n) % block
    order = jnp.arange(n + pad) % n

    def body(row_max, cols):
        d = sqn[:, None] + sqn[cols][None, :] - 2.0 * dots_to_many(
            factors, cols)
        return jnp.maximum(row_max, jnp.max(d, axis=1)), None

    row_max, _ = jax.lax.scan(body, jnp.full((n,), -jnp.inf),
                              order.reshape(-1, block))
    return jnp.argmin(row_max)


def kcenter_greedy(
    factors: Sequence[np.ndarray],
    labeled_mask: np.ndarray,
    budget: int,
    randomize: bool = False,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Select ``budget`` local row indices by greedy k-center over the
    factorized embeddings.  Matches coreset_sampler.coreset(:66-105):
    deterministic mode takes the farthest-point argmax; randomized mode
    draws with D^2 probabilities.  Returns selections in pick order."""
    factors = tuple(jnp.asarray(np.asarray(f), dtype=jnp.float32)
                    for f in factors)
    labeled_mask = np.asarray(labeled_mask, dtype=bool)
    n = labeled_mask.shape[0]
    budget = int(budget)
    if budget <= 0:
        return np.zeros(0, dtype=np.int64)
    if rng is None:
        rng = np.random.default_rng()
    key = jax.random.PRNGKey(int(rng.integers(2 ** 31)))

    sqn = self_sq_norms(factors)
    labeled_idxs = np.flatnonzero(labeled_mask)
    picks_pre: list = []
    if len(labeled_idxs) == 0:
        # Seed point (coreset_sampler.py:95-100): uniform when randomized,
        # else the minimax row.
        if randomize:
            seed_idx = int(rng.integers(n))
        else:
            seed_idx = int(_minimax_row(factors, sqn))
        picks_pre.append(seed_idx)
        labeled_idxs = np.asarray([seed_idx])
        budget -= 1

    min_dist = min_sq_dist_to(factors, sqn, labeled_idxs)
    selectable = np.ones(n, dtype=np.float32)
    selectable[labeled_idxs] = 0.0
    # Opt-in fused Pallas update for the deterministic single-factor scan
    # (AL_TPU_KCENTER_PALLAS=1 on TPU, =interpret for CPU testing) — same
    # picks, one fused HBM pass per step; see ops/kcenter_pallas.py and
    # DESIGN.md §5 for why this stays opt-in.
    pallas_mode = os.environ.get("AL_TPU_KCENTER_PALLAS", "")
    use_pallas = (budget > 0 and not randomize and len(factors) == 1
                  and pallas_mode in ("1", "interpret"))
    picks = None
    if use_pallas:
        try:
            from ..ops import kcenter_pallas as kp
            xt = kp.pad_to_tiles(factors[0])
            n_pad = xt.shape[1]
            sqn_row = jnp.zeros((1, n_pad), jnp.float32).at[0, :n].set(sqn)
            md_row = jnp.full((1, n_pad), jnp.inf,
                              jnp.float32).at[0, :n].set(min_dist)
            sel = jnp.zeros(n_pad, jnp.float32).at[:n].set(
                jnp.asarray(selectable))
            picks = np.asarray(
                _kcenter_scan_pallas(xt, sqn_row, md_row, sel, budget,
                                     pallas_mode == "interpret"),
                dtype=np.int64)
        except Exception as e:
            # A compiled-kernel failure on real hardware (tiling limits,
            # pltpu API drift) must degrade to the XLA scan, not kill the
            # experiment mid-round.  In interpret mode (CI) the opposite:
            # a silent fallback would make the pick-equality pin test
            # compare XLA to XLA and pass vacuously — re-raise there.
            if pallas_mode == "interpret":
                raise
            from ..utils.logging import get_logger
            try:
                # The failure may BE this module's import (pltpu missing
                # on an exotic jax build) — the marker is best-effort, the
                # fallback is not.
                from ..ops import kcenter_pallas as kp
                kp.LAST_FALLBACK_ERROR = repr(e)  # bench A/B reads this
            except ImportError:
                pass
            get_logger().warning(
                f"Pallas k-center update failed ({e!r}); falling back to "
                "the XLA scan")
    if picks is None:
        if budget > 0:
            picks = np.asarray(
                _kcenter_scan(factors, sqn, min_dist,
                              jnp.asarray(selectable), budget,
                              bool(randomize), key),
                dtype=np.int64)
        else:
            picks = np.zeros(0, dtype=np.int64)
    return np.concatenate([np.asarray(picks_pre, dtype=np.int64), picks])


def adaptive_avg_pool_matrix(n_in: int, n_out: int) -> np.ndarray:
    """[n_in, n_out] averaging weights with torch adaptive_avg_pool bin
    edges: bin o covers [floor(o*In/Out), ceil((o+1)*In/Out)).  Pooling a
    vector is then ``v @ M`` (badge_sampler.py:41-44 applies the 2-D pool to
    the rank-1 grad embedding; pooling each factor separately is exact)."""
    m = np.zeros((n_in, n_out), dtype=np.float32)
    for o in range(n_out):
        start = int(np.floor(o * n_in / n_out))
        end = int(np.ceil((o + 1) * n_in / n_out))
        m[start:end, o] = 1.0 / (end - start)
    return m
