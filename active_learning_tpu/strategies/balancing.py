"""Class-balancing acquisition for imbalanced pools (WACV 2020).

Reference: src/query_strategies/balancing_sampler.py:8-136.  Per selection:
if the labeled class distribution is imbalanced relative to the remaining
budget, pick the unlabeled point whose distance to the rarest-class
centroid, normalized by its largest distance to any majority-class
centroid, is smallest; otherwise pick uniformly at random.

The reference runs the whole per-pick distance pass on host NumPy
(:83-125): every selection is a fresh O(N_unlabeled x C x D) pass over the
pool, so 10k picks over a 1.28M-image pool is hours of host time.  Here the
pool embeddings and the eligibility mask live ON DEVICE, sharded over the
mesh's data axis, for the whole query:

  * one O(N) upload, deferred to the FIRST balancing pick — a query that
    stays in the random branch throughout never touches the device;
  * each balancing pick is ONE jitted SPMD call — masked distance pass +
    global argmin across shards — whose host<->device traffic is O(C*D)
    (the centroids) down and ONE scalar (the argmin) back, independent of
    pool size;
  * the host keeps incremental per-class counts and embedding sums
    (O(D) per pick), because the sequential label-peeking update makes the
    pick loop inherently serial.

Precision, disclosed deliberately: the reference's loop mixes float32
embeddings with float64 centroid math (np.zeros defaults, :96-118).  Here
centroid SUMS accumulate in float64 on host, but centers are cast to
float32 for the device pass, whose distances/matmul run in float32
(matmul pinned to Precision.HIGHEST so the MXU doesn't drop to bfloat16).
Two candidates whose true scores agree to ~1e-6 relative may therefore
argmin differently than the float64 host loop — an immaterial tie-break
for acquisition quality, traded for running the pass on the mesh at all.
The oracle test (tests/test_clustering_balancing.py) pins THESE float32
semantics.

Reference quirks preserved deliberately:
  * the normalizer is the MAX distance to the majority centroids despite
    the variable's name (:116-118);
  * centroids use the TRUE labels of just-picked examples immediately
    (label peeking mid-round, like the cheating BalancedRandomSampler);
  * a rarest-class count of zero sets the numerator to 1 (:106-109).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel import mesh as mesh_lib
from .base import Strategy, register_strategy

# Registered step-builders (scripts/al_lint.py recompile-hazard): the
# module-level jitted picks compile once per pool shape by construction;
# any NEW jax.jit here must be named below or the lint fails.
_STEP_BUILDERS = ("_balancing_pick", "_mark_taken", "_set_center_row")


@jax.jit
def _balancing_pick(emb, eligible, centers, maj_mask, rarest, rare_empty):
    """One balancing selection, fully on device (balancing_sampler.py:83-125).

    emb [N, D] and eligible [N] are sharded over the data axis; centers
    [C, D] / maj_mask [C] / rarest / rare_empty are replicated scalars or
    tiny arrays.  Returns the global pool index of the pick.
    """
    d_rare = ((emb - centers[rarest][None, :]) ** 2).sum(axis=1)
    d_rare = jnp.where(rare_empty, jnp.ones_like(d_rare), d_rare)
    # Distances to ALL centroids via the expanded form (one [N, C] matmul),
    # then a masked max over the majority classes only — the static-shape
    # equivalent of the reference's centers[maj] gather (:110-118).
    # HIGHEST precision: at default precision the TPU MXU contracts in
    # bfloat16, whose rounding error in a2 + b2 - 2ab is comparable to
    # small true distances — a near-centroid norm could come out ~0 or
    # negative and flip the argmin toward a majority centroid.
    a2 = (emb ** 2).sum(axis=1, keepdims=True)
    b2 = (centers ** 2).sum(axis=1)[None, :]
    d_all = a2 + b2 - 2.0 * jnp.matmul(
        emb, centers.T, precision=jax.lax.Precision.HIGHEST)
    d_maj = jnp.where(maj_mask[None, :], d_all, -jnp.inf)
    norm = jnp.max(d_maj, axis=1)  # the reference's max (:116)
    score = jnp.where(eligible, d_rare / norm, jnp.inf)
    return jnp.argmin(score)


@jax.jit
def _mark_taken(eligible, idx):
    return eligible.at[idx].set(False)


@jax.jit
def _set_center_row(centers, c, row):
    return centers.at[c].set(row)


def device_pool_state(mesh, embeddings: np.ndarray, eligible: np.ndarray):
    """Upload the pool once: embeddings + eligibility mask, padded to the
    mesh size and sharded over the data axis.  Padded rows are ineligible
    so they can never win the argmin.  On a multi-host mesh each process
    contributes only its own row slice."""
    n = embeddings.shape[0]
    pad = (-n) % mesh.devices.size
    emb = np.ascontiguousarray(
        np.pad(embeddings.astype(np.float32), ((0, pad), (0, 0))))
    elig = np.pad(eligible, (0, pad))
    sharding = mesh_lib.batch_sharding(mesh)
    if mesh_lib.is_multiprocess(mesh):
        rows = mesh_lib.process_local_rows(mesh, n + pad)

        def put(a):
            return jax.make_array_from_process_local_data(
                sharding, np.ascontiguousarray(a[rows]), a.shape)

        return put(emb), put(elig)
    return (jax.device_put(emb, sharding),
            jax.device_put(elig, sharding))


@register_strategy("BalancingSampler")
class BalancingSampler(Strategy):

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._saved_embeddings: Optional[np.ndarray] = None

    def _all_embeddings(self) -> np.ndarray:
        if self.cfg.freeze_feature and self._saved_embeddings is not None:
            return self._saved_embeddings
        all_idxs = np.arange(len(self.al_set), dtype=np.int64)
        emb = self.collect_scores(all_idxs, "embed",
                                  keys=("embedding",))["embedding"]
        if self.cfg.freeze_feature:
            self._saved_embeddings = emb
        return emb

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        ys = self.al_set.targets[: len(self.al_set)]
        idxs_for_query = self.available_query_mask().copy()
        budget = int(min(idxs_for_query.sum(), budget))
        if budget == 0:
            return np.zeros(0, dtype=np.int64), 0
        embeddings = self._all_embeddings()  # float32, like the reference
        n_classes = self.num_classes

        # Deferred to the first balancing pick: random-only queries (the
        # common case while the labeled set stays balanced) never pay the
        # O(N*D) upload or the per-pick device round-trips.
        emb_dev = eligible_dev = None
        # Replicated [C, D] float32 centroid mirror.  Each pick changes
        # exactly one class's sum/count, so after the initial upload the
        # per-pick traffic is ONE [D] row (host float64 -> float32, the
        # same value a full re-upload would carry) instead of [C, D] —
        # 8 KB vs 8 MB per pick at ImageNet-LT scale.
        centers_dev = None

        def center_row(c: int) -> np.ndarray:
            return (sums[c] / (counts[c] + 1e-5)).astype(np.float32)

        # Host-side class bookkeeping, updated incrementally per pick
        # (the reference recomputes from the full labeled set each pick,
        # balancing_sampler.py:96-104 — same value, O(C*D) instead of
        # O(L*D) per step).
        labeled = self.already_labeled_mask()
        counts = np.bincount(ys[labeled], minlength=n_classes
                             ).astype(np.int64)
        # float64 accumulation, like the reference's np.zeros default
        # (:96): a whole labeled set summed in float32 would lose the low
        # bits that separate near-identical centroids.
        sums = np.zeros((n_classes, embeddings.shape[1]), dtype=np.float64)
        np.add.at(sums, ys[labeled], embeddings[labeled])

        selected = []
        for query_count in range(budget):
            mean_count = counts.mean()
            maj = counts > mean_count
            minor = ~maj
            avg_maj = counts[maj].sum() / max(maj.sum(), 1)
            avg_minor = counts[minor].sum() / max(minor.sum(), 1)

            remaining = budget - query_count
            if remaining <= minor.sum() * (avg_maj - avg_minor):
                # Balancing pick: one sharded distance pass + argmin on
                # device; only the centroids go down and one index comes
                # back.
                if emb_dev is None:
                    emb_dev, eligible_dev = device_pool_state(
                        self.mesh, embeddings, idxs_for_query)
                if centers_dev is None:
                    centers = np.stack(
                        [center_row(i) for i in range(n_classes)])
                    centers_dev = mesh_lib.replicate(centers, self.mesh)
                rarest = int(np.argmin(counts))
                small = mesh_lib.replicate(
                    (maj, np.int32(rarest),
                     np.bool_(counts[rarest] == 0)), self.mesh)
                query_idx = int(_balancing_pick(emb_dev, eligible_dev,
                                                centers_dev, *small))
            else:
                # Balanced enough: random pick (:126-128).
                query_idx = int(self.rng.choice(
                    np.flatnonzero(idxs_for_query)))

            idxs_for_query[query_idx] = False
            if eligible_dev is not None:
                eligible_dev = _mark_taken(
                    eligible_dev,
                    mesh_lib.replicate(np.int32(query_idx), self.mesh))
            c = int(ys[query_idx])
            counts[c] += 1
            sums[c] += embeddings[query_idx]
            if centers_dev is not None:
                centers_dev = _set_center_row(
                    centers_dev, *mesh_lib.replicate(
                        (np.int32(c), center_row(c)), self.mesh))
            selected.append(query_idx)

        self.logger.info(f"Number of queried images: {budget}")
        return np.asarray(selected, dtype=np.int64), budget
