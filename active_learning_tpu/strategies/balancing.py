"""Class-balancing acquisition for imbalanced pools (WACV 2020).

Reference: src/query_strategies/balancing_sampler.py:8-136.  Per selection:
if the labeled class distribution is imbalanced relative to the remaining
budget, pick the unlabeled point whose distance to the rarest-class
centroid, normalized by its largest distance to any majority-class
centroid, is smallest; otherwise pick uniformly at random.

The embedding pass over the WHOLE al_set (:39-53) is mesh-parallel here and
cached under ``freeze_feature`` (:34-36, 55-57).  The per-pick loop is host
NumPy: each step is O(N * M) on a few-thousand-row slice and data-dependent
on the previous pick, so there is nothing for the mesh to win.

Reference quirks preserved deliberately:
  * the normalizer is the MAX distance to the majority centroids despite
    the variable's name (:116-118);
  * centroids use the TRUE labels of just-picked examples immediately
    (label peeking mid-round, like the cheating BalancedRandomSampler);
  * a rarest-class count of zero sets the numerator to 1 (:106-109).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .base import Strategy, register_strategy


@register_strategy("BalancingSampler")
class BalancingSampler(Strategy):

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._saved_embeddings: Optional[np.ndarray] = None

    def _all_embeddings(self) -> np.ndarray:
        if self.cfg.freeze_feature and self._saved_embeddings is not None:
            return self._saved_embeddings
        all_idxs = np.arange(len(self.al_set), dtype=np.int64)
        emb = self.collect_scores(all_idxs, "embed",
                                  keys=("embedding",))["embedding"]
        if self.cfg.freeze_feature:
            self._saved_embeddings = emb
        return emb

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        ys = self.al_set.targets[: len(self.al_set)]
        idxs_for_query = self.available_query_mask().copy()
        idxs_labeled = self.already_labeled_mask().copy()
        budget = int(min(idxs_for_query.sum(), budget))
        if budget == 0:
            return np.zeros(0, dtype=np.int64), 0
        embeddings = self._all_embeddings()  # float32, like the reference
        n_classes = self.num_classes

        selected = []
        for query_count in range(budget):
            ys_labeled = ys[idxs_labeled]
            counts = np.bincount(ys_labeled, minlength=n_classes)
            mean_count = counts.mean()
            maj = counts > mean_count
            minor = ~maj
            avg_maj = counts[maj].sum() / max(maj.sum(), 1)
            avg_minor = counts[minor].sum() / max(minor.sum(), 1)

            remaining = budget - query_count
            if remaining <= minor.sum() * (avg_maj - avg_minor):
                # Balancing pick (:83-125).
                emb_labeled = embeddings[idxs_labeled]
                centers = np.zeros((n_classes, embeddings.shape[1]))
                np.add.at(centers, ys_labeled, emb_labeled)
                denom = counts[:, None] + 1e-5
                centers = centers / denom
                rarest = int(np.argmin(counts))
                emb_unlabeled = embeddings[idxs_for_query]

                d_rare = ((emb_unlabeled - centers[rarest]) ** 2).sum(1)
                if counts[rarest] == 0:
                    d_rare = np.ones_like(d_rare)
                centers_maj = centers[maj]
                a2 = (emb_unlabeled ** 2).sum(1, keepdims=True)
                b2 = (centers_maj ** 2).sum(1, keepdims=True)
                d_maj = a2 + b2.T - 2.0 * emb_unlabeled @ centers_maj.T
                norm = d_maj.max(axis=1)  # the reference's max (:116)
                score = d_rare / norm
                local = int(np.argmin(score))
                query_idx = int(np.flatnonzero(idxs_for_query)[local])
            else:
                # Balanced enough: random pick (:126-128).
                query_idx = int(self.rng.choice(
                    np.flatnonzero(idxs_for_query)))

            idxs_for_query[query_idx] = False
            idxs_labeled[query_idx] = True
            selected.append(query_idx)

        self.logger.info(f"Number of queried images: {budget}")
        return np.asarray(selected, dtype=np.int64), budget
