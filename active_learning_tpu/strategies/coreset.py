"""Coreset (k-Center greedy) and BADGE acquisition, plus their partitioned
variants.

Reference: src/query_strategies/coreset_sampler.py:8-133 (k-center greedy
over final embeddings, Sener & Savarese arXiv:1708.00489),
badge_sampler.py:13-78 (randomized k-center over gradient embeddings,
arXiv:1906.03671), partitioned_coreset_sampler.py:9-84 and
partitioned_badge_sampler.py:5-19 (random-partition escape hatch for the
O(N^2) distance matrix, arXiv:2107.14263).

TPU-first differences (see strategies/kcenter.py for the math):
  * the embedding / gradient-embedding pass is mesh-parallel
    (strategies/scoring.py) instead of a single-GPU loader walk;
  * the greedy selection runs fully on device over factorized embeddings
    (batched farthest-first, q picks per pool pass — cfg.kcenter_batch)
    — the N x N matrix the reference materializes
    (coreset_sampler.py:59-64) never exists, which also removes the reason
    partitioning was mandatory at ImageNet scale (it remains supported for
    parity and for bounding the embedding pass itself).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from . import kcenter as kcenter_lib
from .base import Strategy, register_strategy
from .kcenter import kcenter_greedy

Factors = Tuple[np.ndarray, ...]


@register_strategy("CoresetSampler")
class CoresetSampler(Strategy):
    """k-Center greedy: repeatedly pick the unlabeled point farthest from
    the labeled set in final-embedding space (coreset_sampler.py:66-105)."""

    randomize = False
    # The reference caches its pairwise matrix across rounds when features
    # are frozen (coreset_sampler.py:112-121) — embeddings are constant so
    # the factors are cached here instead (smaller, same validity).  BADGE
    # never populates the cache (its query recomputes gradient embeddings
    # every round; the saved_pairwise_l2_dist assignment is absent from
    # badge_sampler.py:60-65).
    cache_factors = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._saved_factors: Optional[Factors] = None

    # -- pool subsetting (coreset_sampler.py:21-41) -----------------------

    def get_idxs_for_coreset(self, return_sep_idxs: bool = False):
        """The index set the selection runs over: all available + all
        labeled (minus eval), with optional ``subset_labeled`` /
        ``subset_unlabeled`` caps.  The unlabeled cap inherits any unused
        labeled quota (coreset_sampler.py:28-34)."""
        idxs_for_query = self.available_query_idxs(shuffle=True)
        idxs_labeled = self.already_labeled_idxs(shuffle=True)
        subset_labeled = self.cfg.subset_labeled
        subset_unlabeled = self.cfg.subset_unlabeled

        if subset_labeled is not None:
            cap_lb = min(subset_labeled, len(idxs_labeled))
            idxs_labeled = idxs_labeled[:cap_lb]
        if subset_unlabeled is not None:
            if subset_labeled is not None:
                cap_ul = subset_labeled + subset_unlabeled - cap_lb
            else:
                cap_ul = subset_unlabeled
            cap_ul = min(cap_ul, len(idxs_for_query))
            idxs_for_query = idxs_for_query[:cap_ul]

        idxs_for_coreset = np.sort(np.concatenate(
            [idxs_for_query, idxs_labeled])).astype(np.int64)
        if return_sep_idxs:
            return idxs_for_coreset, idxs_labeled, idxs_for_query
        return idxs_for_coreset

    # -- embeddings -------------------------------------------------------

    def get_factors(self, idxs: np.ndarray) -> Factors:
        """Factor matrices for the pairwise distances; one mesh-parallel
        embedding pass (coreset_sampler.py:43-57)."""
        out = self.collect_scores(idxs, "embed", keys=("embedding",))
        return (out["embedding"],)

    def _factors_with_cache(self, idxs: np.ndarray) -> Factors:
        subsets_off = (self.cfg.subset_labeled is None
                       and self.cfg.subset_unlabeled is None)
        cacheable = (self.cache_factors and self.cfg.freeze_feature
                     and subsets_off)
        # Cache validity relies on idxs being identical across rounds,
        # which holds exactly when the subset caps are off: the sorted
        # union of available+labeled is all non-eval indices, a constant.
        if cacheable and self._saved_factors is not None:
            return self._saved_factors
        factors = self.get_factors(idxs)
        if cacheable:
            self._saved_factors = factors
        return factors

    # -- speculative plan (the pipelined round) ---------------------------

    # The scoring pass collect_scores will run: fixed per subclass so
    # the speculative plan and query can never disagree on the
    # statistic.
    spec_kind = "embed"
    spec_keys = ("embedding",)

    def speculative_scoring_plan(self):
        """The coming query's embedding pass, rng-free: with the subset
        caps off, ``idxs_for_coreset`` is the SORTED union of available
        and labeled indices — a pure function of the pool masks — even
        though query() builds it from two rng-shuffled views.  With a
        cap on, the subset IS an rng draw, so the round runs
        un-speculated; same when the frozen-feature factor cache already
        holds the answer (nothing will be scored at all)."""
        if (self.cfg.subset_labeled is not None
                or self.cfg.subset_unlabeled is not None):
            return None
        if (self.cache_factors and self.cfg.freeze_feature
                and self._saved_factors is not None):
            return None
        available = self.pool.available_query_idxs(shuffle=False)
        if len(available) == 0:
            return None
        idxs = np.sort(np.concatenate(
            [available, self.pool.labeled_idxs()])).astype(np.int64)
        return {"kind": self.spec_kind, "keys": self.spec_keys,
                "idxs": idxs}

    # -- query ------------------------------------------------------------

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        idxs_for_coreset, _, idxs_for_query = self.get_idxs_for_coreset(
            return_sep_idxs=True)
        if len(idxs_for_query) == 0:
            return np.zeros(0, dtype=np.int64), 0
        factors = self._factors_with_cache(idxs_for_coreset)
        labeled_mask = self.already_labeled_mask()[idxs_for_coreset]
        budget = int(min(len(idxs_for_query), budget))
        picks = kcenter_greedy(factors, labeled_mask, budget,
                               randomize=self.randomize, rng=self.rng,
                               batch_q=self.cfg.kcenter_batch,
                               mesh=self.mesh,
                               pool_sharding=self.trainer.pool_sharding)
        # Pick-time distance-to-labeled, captured from the selection
        # scan's own values (telemetry/diagnostics, DESIGN.md §13) —
        # one gated call, picks unaffected.
        self._record_pick_dist_diagnostics(kcenter_lib.LAST_PICK_DISTS)
        selected = idxs_for_coreset[picks]
        assert len(np.unique(selected)) == len(selected), (
            "k-center selected a duplicate index")
        self.logger.info(f"Number of queried images: {len(selected)}")
        return selected, len(selected)


@register_strategy("BADGESampler")
class BADGESampler(CoresetSampler):
    """Randomized k-center (k-means++ D^2 draws) over gradient embeddings
    (badge_sampler.py:50-78).  The factors are (softmax - onehot, embedding)
    — the outer product is never formed."""

    randomize = True
    cache_factors = False
    spec_kind = "badge"
    spec_keys = ("grad_a", "grad_e")

    def get_factors(self, idxs: np.ndarray) -> Factors:
        out = self.collect_scores(idxs, "badge", keys=("grad_a", "grad_e"))
        return (out["grad_a"], out["grad_e"])


@register_strategy("PartitionedCoresetSampler")
class PartitionedCoresetSampler(CoresetSampler):
    """Random-partition k-center: split labeled and unlabeled separately
    into ``partitions`` equal shards (so every shard sees the same
    labeled/unlabeled balance), run k-center per shard with a proportional
    budget share (partitioned_coreset_sampler.py:36-84)."""

    def speculative_scoring_plan(self):
        """Partitions are rng draws (generate_partition_idxs_list
        shuffles with the experiment rng), so the per-partition scoring
        order cannot be known ahead of the query — no speculation."""
        return None

    def generate_partition_idxs_list(self, input_idxs: np.ndarray):
        idxs = np.array(input_idxs)
        self.rng.shuffle(idxs)
        n, p = len(idxs), self.cfg.partitions
        parts, cum = [], 0
        for i in range(p):
            cur = n // p + int(i < n % p)
            parts.append(idxs[cum:cum + cur])
            cum += cur
        return parts

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        return self._query_partitioned(budget)

    def _query_partitioned(self, budget: int) -> Tuple[np.ndarray, int]:
        if self.cfg.partitions > 1 and self.mesh.devices.size > 1:
            # Partitioning was the reference's ONLY answer past the
            # single-chip memory ceiling; the row-sharded pool
            # (--pool_sharding row, DESIGN.md §2b) scales the
            # no-partition scan with chip count instead — and unlike
            # partitioning it keeps the pick sequence identical to the
            # global greedy.  Kept for parity and statistical variants.
            self.logger.warning(
                f"--partitions {self.cfg.partitions} on a "
                f"{self.mesh.devices.size}-device mesh is a legacy "
                "fallback: --pool_sharding row shards the factor matrix "
                "across chips and selects over the FULL pool "
                "(DESIGN.md §3); partitioning remains only for parity "
                "and statistical variety")
        _, idxs_labeled, idxs_for_query = self.get_idxs_for_coreset(
            return_sep_idxs=True)
        if len(idxs_for_query) == 0:
            return np.zeros(0, dtype=np.int64), 0
        labeled_parts = self.generate_partition_idxs_list(idxs_labeled)
        unlabeled_parts = self.generate_partition_idxs_list(idxs_for_query)

        budget = int(min(len(idxs_for_query), budget))
        p = self.cfg.partitions
        selected = []
        for i in range(p):
            part = np.concatenate(
                [labeled_parts[i], unlabeled_parts[i]]).astype(np.int64)
            cur_budget = budget // p + int(i < budget % p)
            # budget <= total unlabeled and both splits use the same
            # i < n % p rule, so cur_budget <= len(unlabeled_parts[i]).
            if cur_budget == 0 or len(part) == 0:
                continue
            factors = self.get_factors(part)
            labeled_mask = np.zeros(len(part), dtype=bool)
            labeled_mask[:len(labeled_parts[i])] = True
            picks = kcenter_greedy(factors, labeled_mask, cur_budget,
                                   randomize=self.randomize, rng=self.rng,
                                   batch_q=self.cfg.kcenter_batch,
                                   mesh=self.mesh,
                                   pool_sharding=self.trainer.pool_sharding)
            # Per-partition pick distances accumulate into the same
            # round diagnostics (each call refreshes the scan global).
            self._record_pick_dist_diagnostics(
                kcenter_lib.LAST_PICK_DISTS)
            selected.append(part[picks])

        selected = (np.sort(np.concatenate(selected)) if selected
                    else np.zeros(0, dtype=np.int64))
        assert len(np.unique(selected)) == len(selected), (
            "partitioned k-center selected a duplicate index")
        self.logger.info(f"Number of queried images: {len(selected)}")
        return selected, len(selected)


@register_strategy("PartitionedBADGESampler")
class PartitionedBADGESampler(PartitionedCoresetSampler):
    """Partitioned randomized k-center over POOLED gradient embeddings
    (partitioned_badge_sampler.py:14-19: adaptive-pool to 512 dims, then
    the partitioned D^2 selection)."""

    randomize = True
    cache_factors = False

    def get_factors(self, idxs: np.ndarray) -> Factors:
        out = self.collect_scores(idxs, "badge_pool",
                                  keys=("grad_a", "grad_e"))
        return (out["grad_a"], out["grad_e"])
