"""Random and class-balanced-random acquisition.

Reference: src/query_strategies/random_sampler.py:6-33 and
balanced_random_sampler.py:7-101.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ..initial_pool import balanced_allocation
from .base import Strategy, register_strategy


@register_strategy("RandomSampler")
class RandomSampler(Strategy):
    """Uniform random from the unlabeled pool: the pool is pre-shuffled by
    ``available_query_idxs(shuffle=True)`` and the first ``budget`` taken
    (random_sampler.py:21-31)."""

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        idxs = self.available_query_idxs(shuffle=True)
        count = int(min(len(idxs), budget))
        self.logger.info(f"Number of queried images: {count}")
        return idxs[:count], count


@register_strategy("BalancedRandomSampler")
class BalancedRandomSampler(Strategy):
    """CHEATING BASELINE: peeks at the true labels of unlabeled examples to
    draw a class-balanced random batch (balanced_random_sampler.py:9-11).

    The per-class quota is the water-filling allocation over per-class
    availability (the threshold-search loop at
    balanced_random_sampler.py:50-72, shared with the initial-pool
    generator — see initial_pool.balanced_allocation)."""

    def query(self, budget: int) -> Tuple[np.ndarray, int]:
        targets = self.al_set.targets
        avail_mask = self.available_query_mask()
        budget = int(min(avail_mask.sum(), budget))

        counts = np.bincount(targets[avail_mask], minlength=self.num_classes)
        quota = balanced_allocation(counts, budget)

        labeled_idxs = []
        for c in np.flatnonzero(quota):
            class_avail = np.flatnonzero((targets == c) & avail_mask)
            picked = self.rng.permutation(class_avail)[: quota[c]]
            labeled_idxs.append(picked)
        labeled_idxs = np.concatenate(labeled_idxs) if labeled_idxs else \
            np.zeros(0, dtype=np.int64)
        assert np.unique(labeled_idxs).size == budget, (
            "balanced query produced duplicates or wrong count")
        self.logger.info(f"Number of queried images: {budget}")
        return labeled_idxs, budget
