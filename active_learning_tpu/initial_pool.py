"""Seeded initial-pool and eval-split index generation.

Re-implements src/utils/generate_initial_pool.py: ``random`` and
``random_balance`` generation with the water-filling balanced allocation, the
seed-99 eval split and seed-98 initial pool (wired in src/main_al.py:71,83).
The water-filling helper is shared with BalancedRandomSampler
(src/query_strategies/balanced_random_sampler.py:50-79), which uses the same
algorithm.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def balanced_allocation(counts: np.ndarray, total: int) -> np.ndarray:
    """Water-filling: per-class quota summing to ``total``, as balanced as the
    per-class availability allows.

    Equivalent to the threshold-search loops at
    src/utils/generate_initial_pool.py:31-56 and
    src/query_strategies/balanced_random_sampler.py:50-79: every class
    contributes min(count, thres) and the remainder is distributed one extra
    each to the largest classes.
    """
    counts = np.asarray(counts, dtype=np.int64)
    total = int(total)
    if total > counts.sum():
        raise ValueError(
            f"requested {total} samples but only {counts.sum()} available")
    order = np.argsort(counts, kind="stable")
    sorted_counts = counts[order]

    lo, hi = 0, int(sorted_counts.max(initial=0))
    # Find the smallest threshold at which clipping yields >= total.
    while lo < hi:
        mid = (lo + hi) // 2
        if np.minimum(sorted_counts, mid).sum() >= total:
            hi = mid
        else:
            lo = mid + 1
    thres = lo
    quota_sorted = np.minimum(sorted_counts, thres)
    # Classes still above the threshold can give one more each; remove the
    # surplus from the *smallest* of the at-threshold classes, i.e. hand the
    # "+1" extras to the largest classes — matching the reference's
    # ``num_classes_sample_count[-oneadd:] = thres + 1`` after an ascending
    # sort (generate_initial_pool.py:51-53).
    surplus = int(quota_sorted.sum() - total)
    if surplus > 0:
        at_thres = np.flatnonzero(quota_sorted == thres)
        quota_sorted[at_thres[:surplus]] -= 1
    quota = np.empty_like(quota_sorted)
    quota[order] = quota_sorted
    assert quota.sum() == total
    assert (quota <= counts).all()
    return quota


def generate_idxs(
    targets: Sequence[int],
    num_classes: int,
    size: int,
    generation_type: str,
    avoid_idxs: Optional[Sequence[int]] = None,
    random_seed: Optional[int] = None,
) -> np.ndarray:
    """Select ``size`` indices uniformly ("random") or class-balanced
    ("random_balance") from positions not in ``avoid_idxs``.

    Mirrors src/utils/generate_initial_pool.py:8-70, including the quirk
    that a non-divisible ``random_balance`` size is rounded down to a
    multiple of ``num_classes`` (:21-24).
    """
    rng = np.random.default_rng(random_seed)
    targets = np.asarray(targets, dtype=np.int64)
    available = np.arange(len(targets))
    if avoid_idxs is not None and len(avoid_idxs):
        available = np.setdiff1d(available, np.asarray(avoid_idxs))

    if generation_type == "random":
        rng.shuffle(available)
        return available[:size]

    if generation_type == "random_balance":
        if size % num_classes != 0:
            size = size - size % num_classes
        counts = np.bincount(targets[available], minlength=num_classes)
        quota = balanced_allocation(counts, size)
        rng.shuffle(available)
        remaining = quota.copy()
        result = []
        for idx in available:
            if size == 0:
                break
            y = targets[idx]
            if remaining[y] > 0:
                result.append(idx)
                remaining[y] -= 1
                size -= 1
        return np.asarray(result, dtype=np.int64)

    raise ValueError(f"Init pool type '{generation_type}' not implemented")


def generate_eval_idxs(
    targets: Sequence[int],
    num_classes: int,
    ratio: float = 0.1,
    random_seed: Optional[int] = None,
) -> np.ndarray:
    """Class-balanced validation split (generate_initial_pool.py:72-75)."""
    eval_size = int(len(targets) * ratio)
    return generate_idxs(targets, num_classes, eval_size,
                         generation_type="random_balance",
                         random_seed=random_seed)


def generate_init_lb_idxs(
    targets: Sequence[int],
    num_classes: int,
    eval_idxs: Sequence[int],
    init_pool_size: int,
    init_pool_type: str = "random",
    random_seed: Optional[int] = None,
) -> np.ndarray:
    """Round-0 labeled pool, avoiding the eval split
    (generate_initial_pool.py:78-80)."""
    return generate_idxs(targets, num_classes, init_pool_size,
                         generation_type=init_pool_type,
                         avoid_idxs=eval_idxs, random_seed=random_seed)
