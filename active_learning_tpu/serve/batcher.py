"""Async microbatching with bucket padding and bounded admission.

The batcher is the seam between irregular request arrivals and the
executor's fixed-shape jitted steps.  Three rules govern it:

  * **Coalesce, bounded two ways.**  A batch closes when it holds
    ``max_batch`` rows (full-batch flush — immediate, the deadline is
    NOT awaited) or when ``max_latency_ms`` has elapsed since its first
    row arrived (deadline flush — a lone late-night request never waits
    longer than the deadline).  An entry that would overflow the batch
    is carried into the next one whole; entries are never split here
    (``submit`` already chunks oversized requests), so responses always
    slice contiguously out of one batch.
  * **Every dispatched shape is a bucket.**  Real rows are padded up to
    the enclosing geometric bucket (pool.bucket_size — the SAME rule
    that keeps the trainer and k-center recompile-free across AL
    rounds), rounded to a device-mesh multiple.  The bucket ladder is
    enumerable at startup, so the executor pre-compiles every shape the
    request path can ever produce — zero cold compiles on a request.
    Padding rows repeat the batch's first real row with mask 0.0, the
    exact layout contract of data/pipeline.padded_batch_layout; the
    scoring steps are per-example under eval-mode BN, so padded rows
    provably cannot perturb real rows (pinned in tests/test_serve.py
    against an unbatched forward).
  * **Admission is bounded.**  ``queue_depth`` caps the ROWS admitted
    but not yet completed (queued + in flight on device); past it,
    ``submit`` raises ``QueueFullError`` and the server answers 429 +
    Retry-After — explicit backpressure instead of unbounded latency.

Single-threaded discipline: all batcher state lives on the event loop
thread.  The executor completes entries from its own thread via each
entry's ``loop.call_soon_threadsafe``; the row-count decrement comes
back the same way.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..pool import bucket_size

# Lock discipline, statically enforced (scripts/al_lint.py
# lock-discipline): the admitted-row counter is the 429 backpressure
# bound.  Completion callbacks are MARSHALLED to the event loop
# (call_soon_threadsafe), but the bound is too load-bearing to rest on
# that convention alone — every touch of the counter takes the
# admission lock (uncontended in the steady state: nanoseconds), so a
# future resolved off-loop can never silently breach queue_depth.
_GUARDED_BY = {"_pending_rows": "_admission_lock"}

# Default floor for the serve bucket ladder: far below the pool-scan
# floor (256) because a serving microbatch's lower bound is ONE row —
# the ladder must reach down to interactive single-image requests
# without padding them 256-wide.
SERVE_BUCKET_FLOOR = 8


class QueueFullError(Exception):
    """Admission refused: queued + in-flight rows would exceed
    ``queue_depth``.  The server maps this to 429 + Retry-After."""


class BatcherClosedError(Exception):
    """submit() after drain began; the server maps this to 503."""


def serve_buckets(max_batch: int, floor: int = SERVE_BUCKET_FLOOR,
                  n_devices: int = 1) -> List[int]:
    """The complete ladder of batch shapes this service will ever
    dispatch: geometric buckets (pool.bucket_size) covering
    1..max_batch, each rounded up to a multiple of ``n_devices`` so the
    batch axis shards evenly over the mesh.  Sorted ascending; the
    executor warms every entry at startup."""
    max_batch = max(1, int(max_batch))
    floor = max(1, int(floor))
    n_devices = max(1, int(n_devices))
    raw = {bucket_size(n, floor=floor) for n in range(1, max_batch + 1)}
    return sorted({-(-b // n_devices) * n_devices for b in raw})


class _Entry:
    """One contiguous run of rows awaiting results: a whole request, or
    one ≤max_batch chunk of an oversized one."""

    __slots__ = ("images", "n", "future", "want_embed", "offset")

    def __init__(self, images: np.ndarray, future: asyncio.Future,
                 want_embed: bool):
        self.images = images
        self.n = int(images.shape[0])
        self.future = future
        self.want_embed = want_embed
        self.offset = 0  # row offset inside the dispatched batch


class MicroBatcher:
    """Coalesce request entries into bucket-padded microbatches and hand
    them to ``dispatch`` (the executor's thread-safe inbox).

    ``dispatch(host_batch, entries, want_embed)`` receives the padded
    ``{"image", "mask"}`` batch plus the entries (with ``offset`` set)
    whose futures the executor resolves.  ``on_batch`` (optional)
    observes ``(bucket, real_rows)`` per dispatch for the occupancy
    histogram.
    """

    _DRAIN = object()

    def __init__(
        self,
        dispatch: Callable,
        max_batch: int,
        max_latency_ms: float,
        queue_depth: int,
        buckets: Optional[Sequence[int]] = None,
        bucket_floor: int = SERVE_BUCKET_FLOOR,
        n_devices: int = 1,
        on_batch: Optional[Callable] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._dispatch = dispatch
        self.max_batch = int(max_batch)
        self.max_latency_s = float(max_latency_ms) / 1000.0
        self.queue_depth = int(queue_depth)
        self.buckets = list(buckets) if buckets is not None else \
            serve_buckets(max_batch, floor=bucket_floor,
                          n_devices=n_devices)
        self._on_batch = on_batch
        self._clock = clock
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._carry: Optional[_Entry] = None
        self._pending_rows = 0  # admitted, not yet completed
        self._admission_lock = threading.Lock()
        self._closing = False
        self._task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._task = self._loop.create_task(self._run(),
                                            name="al-serve-batcher")

    @property
    def pending_rows(self) -> int:
        with self._admission_lock:
            return self._pending_rows

    # -- admission (event-loop thread) -----------------------------------

    async def submit(self, images: np.ndarray,
                     want_embed: bool = False) -> Dict[str, np.ndarray]:
        """Queue ``images`` (uint8 [n, H, W, C]) and await the per-row
        result dict.  Oversized requests are chunked to ≤max_batch entry
        runs and the chunk results concatenated, so a client batch of
        any size gets one coherent answer."""
        if self._closing:
            raise BatcherClosedError("server is draining")
        n = int(images.shape[0])
        if n == 0:
            raise ValueError("empty request")
        with self._admission_lock:
            pending = self._pending_rows
            if pending + n > self.queue_depth:
                admitted = False
            else:
                # Check-and-increment atomically: two submits racing the
                # bound must not both pass the check and overshoot it.
                self._pending_rows = pending + n
                admitted = True
        if not admitted:
            raise QueueFullError(
                f"{pending} rows pending, request of {n} "
                f"exceeds queue_depth={self.queue_depth}")
        loop = asyncio.get_running_loop()
        entries = []
        for start in range(0, n, self.max_batch):
            chunk = images[start:start + self.max_batch]
            e = _Entry(chunk, loop.create_future(), want_embed)
            # Admission releases PER CHUNK as each future settles (done
            # callbacks fire exactly once, success or failure) — never
            # in bulk when the first chunk of a multi-chunk request
            # fails while its siblings still occupy the inbox/device;
            # a bulk release there would admit new work on top of the
            # orphan rows and breach the queued+in-flight bound.
            e.future.add_done_callback(
                lambda _f, rows=e.n: self._release(rows))
            entries.append(e)
        for e in entries:
            self._inbox.put_nowait(e)
        # gather (not sequential awaits): a failing chunk must not
        # leave later chunks' exceptions unretrieved.
        outs = await asyncio.gather(*(e.future for e in entries))
        if len(outs) == 1:
            return outs[0]
        # Per-row arrays concatenate back into request order; scalar
        # riders (e.g. the served round) take the LAST chunk's value —
        # under a mid-request hot reload that is the newest round any
        # of the rows saw.
        return {k: (outs[-1][k] if np.ndim(outs[0][k]) == 0
                    else np.concatenate([o[k] for o in outs], axis=0))
                for k in outs[0]}

    # -- the coalescing loop ---------------------------------------------

    async def _run(self) -> None:
        draining = False
        while not draining:
            first = self._carry
            self._carry = None
            if first is None:
                got = await self._inbox.get()
                if got is self._DRAIN:
                    break
                first = got
            batch = [first]
            rows = first.n
            deadline = self._clock() + self.max_latency_s
            while rows < self.max_batch:
                timeout = deadline - self._clock()
                if timeout <= 0:
                    break  # deadline flush
                try:
                    got = await asyncio.wait_for(self._inbox.get(), timeout)
                except asyncio.TimeoutError:
                    break  # deadline flush
                if got is self._DRAIN:
                    draining = True
                    break
                if rows + got.n > self.max_batch:
                    self._carry = got  # whole-entry carry; flush now
                    break
                batch.append(got)
                rows += got.n
            self._flush(batch, rows)
        # Drain: flush everything still queued immediately — no deadline
        # waits, no new admissions (submit raises BatcherClosedError).
        leftover = [self._carry] if self._carry is not None else []
        self._carry = None
        while not self._inbox.empty():
            got = self._inbox.get_nowait()
            if got is not self._DRAIN:
                leftover.append(got)
        batch, rows = [], 0
        for e in leftover:
            if rows + e.n > self.max_batch:
                self._flush(batch, rows)
                batch, rows = [], 0
            batch.append(e)
            rows += e.n
        if batch:
            self._flush(batch, rows)

    def _flush(self, batch: List[_Entry], rows: int) -> None:
        if not batch:
            return
        bucket = next((b for b in self.buckets if b >= rows),
                      self.buckets[-1])
        images = (batch[0].images if len(batch) == 1
                  else np.concatenate([e.images for e in batch], axis=0))
        pad = bucket - rows
        mask = np.ones(bucket, dtype=np.float32)
        if pad:
            # padded_batch_layout's contract: pad rows repeat the first
            # real row, mask 0.0 — identical layout to the offline
            # scoring pipeline, so the same compiled step serves both.
            images = np.concatenate(
                [images, np.repeat(images[:1], pad, axis=0)], axis=0)
            mask[rows:] = 0.0
        off = 0
        for e in batch:
            e.offset = off
            off += e.n
        if self._on_batch is not None:
            self._on_batch(bucket, rows)
        self._dispatch({"image": images, "mask": mask}, list(batch),
                       any(e.want_embed for e in batch))

    # -- completion + drain ----------------------------------------------

    def _release(self, rows: int) -> None:
        """Per-chunk admission release (future done callback, loop
        thread)."""
        with self._admission_lock:
            self._pending_rows -= rows

    async def drain(self, poll_s: float = 0.01,
                    timeout_s: Optional[float] = None) -> None:
        """Stop admitting, flush every queued entry, and wait until all
        admitted rows have completed.  The executor must keep running
        until this returns — it is what resolves the futures."""
        self._closing = True
        self._inbox.put_nowait(self._DRAIN)
        if self._task is not None:
            await self._task
        t0 = self._clock()
        while self.pending_rows > 0:
            if timeout_s is not None and self._clock() - t0 > timeout_s:
                raise asyncio.TimeoutError(
                    f"drain: {self.pending_rows} rows still pending "
                    f"after {timeout_s}s")
            await asyncio.sleep(poll_s)
