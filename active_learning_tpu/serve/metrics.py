"""Serving-side observability: counters, a latency reservoir, and the
batch-occupancy histogram.

Request latency is THIS subsystem's headline metric (round wall-clock
is the driver's), so the reservoir keeps the most recent window of
per-request latencies and serves p50/p99 on demand — the same numbers
``scripts/serve_loadgen.py`` measures from the client side and the
``serve_throughput`` bench phase records.  The occupancy histogram
(real rows per dispatched bucket) is the direct readout of how well the
microbatcher is filling the shapes it pays for: a service living at
occupancy 1 in a 64-bucket is latency-bound, one pegged at max_batch is
throughput-bound and a queue-depth candidate.

Thread discipline: the event loop thread and the executor thread both
write; everything is under one lock (counters are tiny, contention is
nil at any realistic qps).
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Dict, Optional


def percentile(sorted_vals, q: float) -> Optional[float]:
    """Nearest-rank percentile over an ascending list; None when empty.
    Shared convention with scripts/serve_loadgen.py so server- and
    client-side p50/p99 are comparable."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return float(sorted_vals[idx])


class ServeMetrics:
    def __init__(self, window: int = 8192):
        self._lock = threading.Lock()
        self._latencies = collections.deque(maxlen=window)
        self.requests: Dict[str, int] = collections.defaultdict(int)
        self.responses: Dict[int, int] = collections.defaultdict(int)
        # occupancy[bucket][real_rows] = dispatch count
        self.occupancy: Dict[int, Dict[int, int]] = {}
        self.rows_served = 0
        self.started = time.monotonic()

    def record_request(self, endpoint: str) -> None:
        with self._lock:
            self.requests[endpoint] += 1

    def record_response(self, status: int, latency_s: Optional[float],
                        rows: int = 0) -> None:
        with self._lock:
            self.responses[status] += 1
            self.rows_served += rows
            if latency_s is not None:
                self._latencies.append(latency_s)

    def record_batch(self, bucket: int, rows: int) -> None:
        with self._lock:
            hist = self.occupancy.setdefault(int(bucket), {})
            hist[int(rows)] = hist.get(int(rows), 0) + 1

    def snapshot(self) -> Dict:
        with self._lock:
            lats = sorted(self._latencies)
            uptime = time.monotonic() - self.started
            n_ok = self.responses.get(200, 0)
            return {
                "uptime_s": round(uptime, 1),
                "requests": dict(self.requests),
                "responses": {str(k): v for k, v in self.responses.items()},
                "rows_served": self.rows_served,
                "qps": round(n_ok / uptime, 2) if uptime > 0 else 0.0,
                "latency_ms": {
                    "p50": _ms(percentile(lats, 0.50)),
                    "p99": _ms(percentile(lats, 0.99)),
                    "n": len(lats),
                },
                "batch_occupancy": {
                    str(b): {str(r): c for r, c in sorted(h.items())}
                    for b, h in sorted(self.occupancy.items())
                },
            }


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1000.0, 3)


def prometheus_samples(snap: Dict) -> list:
    """The enriched /metrics snapshot (server._metrics output) as
    (name, labels, value) samples for telemetry/prom.render — the
    ``?format=prometheus`` view.  Counter-like values stay gauges with a
    _total suffix: they are process-lifetime snapshots and reset with
    the process."""
    samples = [
        ("al_serve_uptime_seconds", None, snap.get("uptime_s")),
        ("al_serve_rows_served_total", None, snap.get("rows_served")),
        ("al_serve_qps", None, snap.get("qps")),
        ("al_serve_served_round", None, snap.get("served_round")),
    ]
    for endpoint, count in sorted((snap.get("requests") or {}).items()):
        samples.append(("al_serve_requests_total",
                        {"endpoint": endpoint}, count))
    for status, count in sorted((snap.get("responses") or {}).items()):
        samples.append(("al_serve_responses_total",
                        {"status": str(status)}, count))
    lat = snap.get("latency_ms") or {}
    for q, key in (("0.5", "p50"), ("0.99", "p99")):
        if lat.get(key) is not None:
            samples.append(("al_serve_request_latency_ms",
                            {"quantile": q}, lat[key]))
    samples.append(("al_serve_latency_window_size", None, lat.get("n")))
    for bucket, hist in sorted((snap.get("batch_occupancy") or {}).items()):
        for rows, count in sorted(hist.items()):
            samples.append(("al_serve_batch_occupancy_total",
                            {"bucket": str(bucket), "rows": str(rows)},
                            count))
    queue = snap.get("queue") or {}
    samples.append(("al_serve_queue_pending_rows", None,
                    queue.get("pending_rows")))
    samples.append(("al_serve_queue_depth", None, queue.get("depth")))
    ex = snap.get("executor") or {}
    for key in ("batches", "rows", "reloads"):
        if key in ex:
            samples.append((f"al_serve_executor_{key}_total", None,
                            ex[key]))
    compiles = snap.get("compiles") or {}
    # THE serving contract, scrapable: 0 after warmup, forever.
    samples.append(("al_serve_request_path_compiles", None,
                    compiles.get("request_path_compiles")))
    for step, count in sorted((compiles.get("per_step") or {}).items()):
        samples.append(("al_serve_jit_cache_entries",
                        {"step": step}, count))
    # The per-model acquisition-score histogram + live-vs-checkpoint
    # drift (telemetry/diagnostics.ServeScoreDrift): the histogram is
    # exposed Prometheus-style (cumulative buckets with ``le`` labels +
    # _count/_sum), the drift gauges ride beside it — the online drift
    # signal of DESIGN.md §13.
    drift = snap.get("score_drift") or {}
    live = drift.get("live") or {}
    counts = live.get("counts") or []
    if counts:
        key = drift.get("key", "score")
        lo, hi = live.get("lo", 0.0), live.get("hi", 1.0)
        bins = max(1, int(live.get("bins", len(counts))))
        log1p = live.get("transform") == "log1p"
        cum = 0
        for i, c in enumerate(counts):
            cum += int(c)
            edge = lo + (i + 1) * (hi - lo) / bins
            if log1p:
                # The ladder is linear in TRANSFORMED space; `le`
                # labels must be in score space or every scraper
                # misreads the distribution.
                edge = math.expm1(edge)
            samples.append(("al_serve_score_hist_bucket",
                            {"key": key, "le": f"{edge:.6g}"}, cum))
        samples.append(("al_serve_score_hist_bucket",
                        {"key": key, "le": "+Inf"}, cum))
        samples.append(("al_serve_score_hist_count", {"key": key},
                        live.get("n")))
        samples.append(("al_serve_score_hist_sum", {"key": key},
                        live.get("sum")))
    if drift.get("baseline_round") is not None:
        samples.append(("al_serve_score_baseline_round", None,
                        drift.get("baseline_round")))
    for metric in ("psi", "js"):
        if drift.get(metric) is not None:
            samples.append((f"al_serve_score_drift_{metric}", None,
                            drift[metric]))
    return samples
