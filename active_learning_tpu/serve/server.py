"""The asyncio HTTP front end of the scoring service.

Stdlib only (asyncio streams + a minimal HTTP/1.1 parser) — the
framework's no-new-dependencies rule holds on the serving path too.
Endpoints:

  POST /v1/predict   {"instances": [[...]]} | {"b64": ..., "shape": [...]}
                     -> {"round", "predictions": [{"pred", "confidence",
                         "margin"}]}
  POST /v1/score     same request schema (+ optional "embedding": true)
                     -> {"round", "scores": [{"pred", "confidence",
                         "margin", "entropy"}], "embedding"?: [[...]]}
  POST /v1/profile   {"seconds": 1.0} -> a BOUNDED device-truth capture
                     window under live load (telemetry/profiler.py,
                     the one gated jax.profiler API): the window opens,
                     traffic keeps flowing, and the response carries
                     device_busy_frac / collective_frac /
                     per-primitive collective counts plus the trace +
                     summary paths (artifacts land in a SERVER-chosen
                     temp dir named in the response — no client-chosen
                     path, no remote filesystem-write primitive).  One
                     window at a time (409 while one is open); seconds
                     clamped to MAX_SERVE_CAPTURE_S; a window that
                     produces no trace is a 500, never a 200.
  GET  /healthz      liveness + the served round, bucket ladder, and
                     image shape (the loadgen reads the shape here)
  GET  /metrics      ServeMetrics snapshot + executor/batcher state,
                     including the compile counter (request_path_compiles
                     MUST stay 0 after warmup)

Backpressure is explicit: when admission would exceed ``queue_depth``
rows the server answers **429 with Retry-After** instead of queueing
unboundedly — the client-visible contract of the batcher's bounded
admission.  During drain new work gets 503.

Graceful drain (SIGTERM): stop accepting connections, let the batcher
flush and every admitted request complete, stop the executor, exit 0.
In-flight requests are never dropped (pinned by tests/test_serve.py's
SIGTERM subprocess test).

Request bodies: images travel either as nested JSON lists
(``instances``) or — the efficient path the loadgen uses — as
``{"b64": base64(raw uint8 bytes), "shape": [n, h, w, c]}``.
"""

from __future__ import annotations

import asyncio
import base64
import binascii
import json
from typing import Dict, Optional, Tuple

import numpy as np

from .batcher import BatcherClosedError, MicroBatcher, QueueFullError
from .executor import DeviceExecutor
from .metrics import ServeMetrics
from ..config import ServeConfig
from ..utils.logging import get_logger

MAX_BODY_BYTES = 256 << 20  # one request can carry a full max_batch of 224px


class _HttpError(Exception):
    def __init__(self, status: int, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = headers or {}


class ScoringServer:
    def __init__(self, executor: DeviceExecutor, cfg: ServeConfig,
                 metrics: Optional[ServeMetrics] = None):
        self.executor = executor
        self.cfg = cfg
        self.metrics = metrics or ServeMetrics()
        self.logger = get_logger()
        self.batcher: Optional[MicroBatcher] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._draining = False
        self.port: Optional[int] = None

    # -- lifecycle -------------------------------------------------------

    async def start(self) -> None:
        """Warm every bucket, start the executor thread and the batcher,
        then open the listener — requests are only admissible once zero
        cold-compile on the request path is already true."""
        n_dev = self.executor.mesh.devices.size
        self.batcher = MicroBatcher(
            dispatch=self.executor.submit_batch,
            max_batch=self.cfg.max_batch,
            max_latency_ms=self.cfg.max_latency_ms,
            queue_depth=self.cfg.queue_depth,
            bucket_floor=self.cfg.bucket_floor,
            n_devices=n_dev,
            on_batch=self.metrics.record_batch,
        )
        self.executor.warmup(self.batcher.buckets)
        self.executor.start()
        self.batcher.start()
        self._server = await asyncio.start_server(
            self._client, self.cfg.host, self.cfg.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self.logger.info(
            f"serve: listening on http://{self.cfg.host}:{self.port} "
            f"(buckets {self.batcher.buckets}, round "
            f"{self.executor.served_round})")

    async def drain(self) -> None:
        """SIGTERM path: close the listener, complete everything
        admitted, stop the device loop."""
        if self._draining:
            return
        self._draining = True
        self.logger.info("serve: drain started (SIGTERM)")
        if self._server is not None:
            self._server.close()
        try:
            await self.batcher.drain(timeout_s=self.cfg.drain_timeout_s)
        finally:
            # The executor stops AFTER the batcher's queue emptied: its
            # shutdown sentinel is FIFO behind every flushed batch.
            await asyncio.get_running_loop().run_in_executor(
                None, self.executor.stop)
        if self._server is not None:
            await self._server.wait_closed()
        self.logger.info("serve: drained cleanly")

    # -- connection handling ---------------------------------------------

    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    req = await _read_request(reader)
                except _HttpError as e:
                    # A malformed head has no trustworthy framing left:
                    # answer and close.
                    _write_response(writer, e.status, {"error": e.message},
                                    e.headers, keep_alive=False)
                    await writer.drain()
                    break
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if req is None:
                    break
                method, path, headers, body = req
                loop = asyncio.get_running_loop()
                t0 = loop.time()
                status, payload, extra = await self._route(method, path,
                                                           body)
                rows = payload.pop("__rows__", 0) if isinstance(
                    payload, dict) else 0
                self.metrics.record_response(
                    status, loop.time() - t0 if method == "POST" else None,
                    rows=rows)
                keep = (headers.get("connection", "").lower()
                        != "close") and not self._draining
                try:
                    _write_response(writer, status, payload, extra,
                                    keep_alive=keep)
                    await writer.drain()
                except (ConnectionError, OSError):
                    # The peer vanished mid-response (churny clients,
                    # LB probes): a silent close, not an unhandled-task
                    # traceback per disconnect.
                    break
                if not keep:
                    break
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001 - peer may already be gone
                pass

    async def _route(self, method: str, path: str, body: bytes
                     ) -> Tuple[int, Dict, Dict[str, str]]:
        path, _, query = path.partition("?")
        try:
            if method == "GET" and path == "/healthz":
                return 200, self._healthz(), {}
            if method == "GET" and path == "/metrics":
                from urllib.parse import parse_qs
                fmt = (parse_qs(query).get("format") or [""])[0]
                if fmt == "prometheus":
                    # Text exposition for stock scrapers; the JSON view
                    # stays the default (loadgen/bench read it).
                    return 200, self._metrics_prometheus(), {
                        "Content-Type":
                            "text/plain; version=0.0.4; charset=utf-8"}
                if fmt and fmt != "json":
                    raise _HttpError(400, f"unknown metrics format "
                                          f"{fmt!r}; use json or "
                                          "prometheus")
                return 200, self._metrics(), {}
            if method == "POST" and path in ("/v1/predict", "/v1/score"):
                self.metrics.record_request(path)
                if self._draining:
                    raise _HttpError(503, "server is draining")
                return await self._score(path, body)
            if method == "POST" and path == "/v1/profile":
                self.metrics.record_request(path)
                if self._draining:
                    raise _HttpError(503, "server is draining")
                return await self._profile(body)
            raise _HttpError(404, f"no route for {method} {path}")
        except _HttpError as e:
            return e.status, {"error": e.message}, e.headers
        except (QueueFullError,) as e:
            # Explicit backpressure: bounded admission, never unbounded
            # queueing.  Retry-After 1s: one max_latency window plus the
            # device's worst-case batch is well under a second.
            return 429, {"error": str(e)}, {"Retry-After": "1"}
        except BatcherClosedError as e:
            return 503, {"error": str(e)}, {}
        except Exception as e:  # noqa: BLE001 - request isolation
            self.logger.exception("serve: request failed")
            return 500, {"error": repr(e)}, {}

    # -- endpoints --------------------------------------------------------

    async def _score(self, path: str, body: bytes
                     ) -> Tuple[int, Dict, Dict[str, str]]:
        req = _parse_json(body)
        images = _decode_images(req, self.executor.image_shape)
        if images.shape[0] > self.cfg.queue_depth:
            # Permanently inadmissible (it could never fit the row
            # bound even on an idle server): a non-retryable 413, not a
            # 429 that compliant clients would retry forever.
            raise _HttpError(
                413, f"request of {images.shape[0]} rows exceeds the "
                     f"server's queue_depth={self.cfg.queue_depth}; "
                     "split the request")
        want_embed = bool(req.get("embedding")) and path == "/v1/score"
        out = await self.batcher.submit(images, want_embed=want_embed)
        rnd = int(out.get("round", self.executor.served_round))
        n = images.shape[0]
        if path == "/v1/predict":
            rows = [{"pred": int(out["pred"][i]),
                     "confidence": float(out["confidence"][i]),
                     "margin": float(out["margin"][i])}
                    for i in range(n)]
            return 200, {"round": rnd, "predictions": rows,
                         "__rows__": n}, {}
        rows = [{"pred": int(out["pred"][i]),
                 "confidence": float(out["confidence"][i]),
                 "margin": float(out["margin"][i]),
                 "entropy": float(out["entropy"][i])}
                for i in range(n)]
        resp: Dict = {"round": rnd, "scores": rows, "__rows__": n}
        if want_embed:
            # tolist() does the whole conversion in C; a Python float()
            # loop here would block the event loop (and the batcher's
            # deadline timer) for n*D calls per request.
            resp["embedding"] = np.asarray(
                out["embedding"], dtype=np.float64).tolist()
        return 200, resp, {}

    async def _profile(self, body: bytes) -> Tuple[int, Dict,
                                                   Dict[str, str]]:
        """A bounded device-truth capture under live load.  The blocking
        window (open -> sleep -> close -> parse) runs in a worker thread
        so the event loop keeps serving THROUGH the window — that live
        traffic is exactly what the capture exists to observe.  One
        window at a time process-wide (the profiler's own gate); a
        second request while one is open gets 409.  Capture overhead is
        real: the profiler's python tracer slows every request served
        during the window and trace parse time grows with traffic —
        exactly why windows are seconds-clamped and one-at-a-time (an
        ops probe, not a monitoring mode)."""
        import tempfile

        from ..telemetry import profiler as profiler_lib

        req = _parse_json(body)
        seconds = req.get("seconds", 1.0)
        if isinstance(seconds, bool) or not isinstance(seconds,
                                                       (int, float)) \
                or not seconds > 0:
            raise _HttpError(400, "seconds must be a positive number "
                                  f"(<= {profiler_lib.MAX_SERVE_CAPTURE_S}"
                                  ", clamped)")
        if "dir" in req:
            # No client-chosen output path: every other endpoint never
            # writes files, and a server on a non-loopback host must
            # not hand remote callers a filesystem-write primitive.
            # The response names where the artifacts landed.
            raise _HttpError(400, "dir is not accepted; artifacts land "
                                  "in a server-chosen directory named "
                                  "in the response")
        out_dir = tempfile.mkdtemp(prefix="al_serve_profile_")
        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(
                None, profiler_lib.serve_capture, out_dir, float(seconds))
        except profiler_lib.CaptureBusyError as e:
            raise _HttpError(409, str(e))
        if not result.get("ok"):
            # The window opened but produced nothing to parse: a failed
            # capture must be status-coded like every other error here,
            # not a 200 an ops script would read as success.
            self.logger.warning(
                f"serve: profile window failed: {result.get('error')}")
            return 500, result, {}
        self.logger.info(
            f"serve: profile window captured -> {out_dir} "
            f"(busy={result.get('device_busy_frac')}, "
            f"collective={result.get('collective_frac')})")
        return 200, result, {}

    def _healthz(self) -> Dict:
        return {
            "ok": True,
            "round": self.executor.served_round,
            "image_shape": list(self.executor.image_shape),
            "buckets": list(self.batcher.buckets),
            "max_batch": self.cfg.max_batch,
            "draining": self._draining,
        }

    def _metrics(self) -> Dict:
        snap = self.metrics.snapshot()
        with self.executor._lock:
            ex = dict(self.executor.stats)
        snap["executor"] = ex
        snap["served_round"] = self.executor.served_round
        snap["queue"] = {
            "pending_rows": self.batcher.pending_rows,
            "depth": self.cfg.queue_depth,
        }
        snap["compiles"] = {
            "per_step": self.executor.compile_counts(),
            # THE serving contract: 0 after warmup, forever.
            "request_path_compiles": self.executor.request_path_compiles(),
        }
        # The per-model score histogram + live-vs-checkpoint drift
        # (telemetry/diagnostics.ServeScoreDrift, DESIGN.md §13).
        # getattr: stub executors (tests) carry no drift tracker.
        drift = getattr(self.executor, "score_drift", None)
        if drift is not None:
            snap["score_drift"] = drift.snapshot()
        return snap

    def _metrics_prometheus(self) -> str:
        """The same snapshot as text exposition (format 0.0.4) through
        the shared encoder (telemetry/prom.py) — both workloads are
        monitorable by stock Prometheus tooling."""
        from ..telemetry import prom
        from .metrics import prometheus_samples
        return prom.render(prometheus_samples(self._metrics()))


# -- wire helpers ------------------------------------------------------------

async def _read_request(reader: asyncio.StreamReader):
    """One HTTP/1.1 request -> (method, path, headers, body); None on a
    cleanly closed connection."""
    try:
        head = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise
    lines = head.decode("latin-1").split("\r\n")
    try:
        method, path, _version = lines[0].split(" ", 2)
    except ValueError:
        raise _HttpError(400, f"malformed request line {lines[0]!r}")
    headers = {}
    for ln in lines[1:]:
        if ":" in ln:
            k, v = ln.split(":", 1)
            headers[k.strip().lower()] = v.strip()
    try:
        length = int(headers.get("content-length", "0") or "0")
    except ValueError:
        raise _HttpError(400, "malformed Content-Length")
    if length < 0:
        raise _HttpError(400, "malformed Content-Length")
    if length > MAX_BODY_BYTES:
        raise _HttpError(413, f"body of {length} bytes exceeds "
                              f"{MAX_BODY_BYTES}")
    body = await reader.readexactly(length) if length else b""
    return method.upper(), path, headers, body


def _write_response(writer: asyncio.StreamWriter, status: int,
                    payload, extra_headers: Dict[str, str],
                    keep_alive: bool) -> None:
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              409: "Conflict", 413: "Payload Too Large",
              429: "Too Many Requests", 500: "Internal Server Error",
              503: "Service Unavailable"}.get(status, "")
    extra_headers = dict(extra_headers)
    if isinstance(payload, str):
        # Text payloads (the Prometheus exposition view) carry their own
        # Content-Type via extra_headers.
        body = payload.encode()
        ctype = extra_headers.pop("Content-Type",
                                  "text/plain; charset=utf-8")
    else:
        body = json.dumps(payload).encode()
        ctype = "application/json"
    head = [f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(body)}",
            f"Connection: {'keep-alive' if keep_alive else 'close'}"]
    head += [f"{k}: {v}" for k, v in extra_headers.items()]
    writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)


def _parse_json(body: bytes) -> Dict:
    try:
        req = json.loads(body.decode() or "{}")
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise _HttpError(400, f"invalid JSON body: {e}")
    if not isinstance(req, dict):
        raise _HttpError(400, "body must be a JSON object")
    return req


def _decode_images(req: Dict, image_shape) -> np.ndarray:
    """{"instances": nested lists} or {"b64": ..., "shape": [n,h,w,c]}
    -> uint8 [n, H, W, C], validated against the served model's input
    shape — a shape the buckets were not compiled for must be rejected
    at the door, not discovered as a request-path compile."""
    h, w, c = image_shape
    if "b64" in req:
        shape = req.get("shape")
        # Every entry must be a true non-negative JSON integer — floats
        # or digit strings would survive the len check only to blow up
        # in reshape as a 500; a malformed request is a 400.
        if (not isinstance(shape, (list, tuple)) or len(shape) != 4
                or not all(isinstance(d, int)
                           and not isinstance(d, bool)
                           and d >= 0 for d in shape)):
            raise _HttpError(400, "b64 payloads need shape [n, h, w, c] "
                                  "of non-negative integers")
        try:
            raw = base64.b64decode(req["b64"], validate=True)
        except (binascii.Error, TypeError, ValueError) as e:
            raise _HttpError(400, f"invalid base64 payload: {e}")
        n = int(shape[0])
        if n <= 0:
            raise _HttpError(400, "empty request")
        if len(raw) != int(np.prod(shape)):
            raise _HttpError(400, f"payload of {len(raw)} bytes does not "
                                  f"match shape {list(shape)}")
        images = np.frombuffer(raw, dtype=np.uint8).reshape(shape)
    elif "instances" in req:
        try:
            images = np.asarray(req["instances"], dtype=np.uint8)
        except (ValueError, TypeError) as e:
            raise _HttpError(400, f"invalid instances payload: {e}")
        if images.ndim == 3:
            images = images[None]
        if images.ndim != 4 or images.shape[0] == 0:
            raise _HttpError(400, "instances must be [n, h, w, c] uint8")
    else:
        raise _HttpError(400, "body needs 'instances' or 'b64'+'shape'")
    if tuple(images.shape[1:]) != (h, w, c):
        raise _HttpError(
            400, f"rows of shape {list(images.shape[1:])} do not match "
                 f"the served model's input {[h, w, c]}")
    return np.ascontiguousarray(images)
