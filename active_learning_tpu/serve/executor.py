"""The single device-executor loop behind the scoring service.

One thread owns the accelerator: it drains bucket-padded microbatches
from a thread-safe inbox, runs the jitted scoring steps over the
persistent mesh, and resolves each request entry's future on its event
loop.  Design decisions, each load-bearing:

  * **The steps ARE the offline steps.**  Prediction and acquisition
    scores come from strategies/scoring.make_prob_stats_step and
    make_embed_step — the same factories every sampler's offline pass
    uses — so a served score is bit-for-bit the offline score at the
    same batch shape (pinned in tests/test_serve.py).  No serving-only
    numerics to drift.
  * **Zero request-path compiles.**  ``warmup()`` runs every step over
    every bucket in the batcher's ladder once, before the first request
    is admitted; with the persistent XLA compilation cache enabled
    (experiment/driver.enable_compilation_cache — the serve CLI turns
    it on) those warmup compiles are disk hits after the first server
    start on a machine.  ``compile_counts()`` exposes the jit caches'
    sizes (the tests/test_compile_reuse.py counter) so /metrics — and
    the serve_throughput bench phase — can assert the request path
    never compiled.
  * **Double-buffered H2D.**  The inbox drain is wrapped in
    data/cache.device_prefetch: a feeder thread shards + dispatches the
    host->device transfer of batch n+1 while batch n computes, so
    serving throughput is bounded by max(host, PCIe, device), the same
    discipline as the offline pool scan's streaming fallback.
  * **Hot checkpoint reload between batches.**  The executor polls the
    experiment's checkpoint directory at a bounded cadence and swaps in
    a newer round's ``best_rd_{n}`` between batches — a running AL
    experiment's freshest model is served without restarting.  The
    probe is the SHARED ``train/checkpoint.BestCkptWatcher`` (the same
    helper the pipelined round's speculative scorer uses): writes are
    atomic (tmp + rename) so a reload can never observe a torn file,
    and the monotonic (round, epoch) publish tag makes two publishes
    within one mtime granule distinguishable.  Variables are replicated
    fresh and the old tree dropped; the jitted steps are
    weight-agnostic, so a reload costs no recompile.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..parallel import mesh as mesh_lib
from ..strategies import scoring
from ..telemetry import diagnostics as diag_lib
from ..train import checkpoint as ckpt_lib
from ..utils.logging import get_logger

_SHUTDOWN = object()

# Keys the prob-stats step yields that /v1/predict and /v1/score serve.
STAT_KEYS = ("pred", "confidence", "margin", "entropy")


class DeviceExecutor:
    """Owns the mesh, the variables, and the one compute thread.

    ``model``/``view`` define the scoring computation; ``variables``
    seeds the weights (host pytree — e.g. checkpoint.load_variables
    output).  ``ckpt_dir`` (optional) enables hot reload: the newest
    ``best_rd_{n}.msgpack`` under it is loaded at construction when
    ``variables`` is None, and re-polled every ``reload_every_s``
    between batches.
    """

    def __init__(
        self,
        model,
        view,
        mesh,
        image_shape: Tuple[int, int, int],
        variables: Optional[Dict[str, Any]] = None,
        ckpt_dir: Optional[str] = None,
        reload_every_s: float = 5.0,
        prefetch_depth: int = 2,
        host_s2d: bool = False,
    ):
        self.model = model
        self.view = view
        self.mesh = mesh
        # Client-facing row shape; with host_s2d the space-to-depth
        # re-layout (the s2d stem's input contract, data/pipeline.py —
        # same transform the offline scoring pipeline applies) happens
        # on the feeder thread, invisible to clients.
        self.host_s2d = bool(host_s2d)
        self.image_shape = tuple(image_shape)
        self.ckpt_dir = ckpt_dir
        self.reload_every_s = float(reload_every_s)
        self.prefetch_depth = int(prefetch_depth)
        self.logger = get_logger()

        self.served_round = -1
        self._watcher = (ckpt_lib.BestCkptWatcher(ckpt_dir)
                         if ckpt_dir is not None else None)
        if variables is None:
            if ckpt_dir is None:
                raise ValueError("need variables or ckpt_dir")
            variables = self._load_latest(required=True)
        self._variables = mesh_lib.replicate(variables, mesh)

        # The offline factories — served outputs match offline scores
        # bit-for-bit at the same batch shape.
        self._steps: Dict[str, Callable] = {
            "prob_stats": scoring.make_prob_stats_step(model, view),
            "embed": scoring.make_embed_step(model, view, with_probs=True),
        }
        self._inq: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._last_reload_check = 0.0
        self._lock = threading.Lock()
        self.stats = {"batches": 0, "rows": 0, "reloads": 0,
                      "warm_buckets": []}
        self._compile_baseline: Optional[Dict[str, int]] = None
        # Online score drift (telemetry/diagnostics.ServeScoreDrift,
        # DESIGN.md §13): every served batch's margin folds into a live
        # histogram; a hot reload snapshots it as the checkpoint-time
        # baseline, and /metrics serves the live-vs-baseline PSI/JS —
        # the per-model drift signal the streaming-AL loop (ROADMAP
        # item 3) consumes.  Host-pure numpy over arrays the request
        # path already fetched; its own lock (observe on this thread,
        # snapshot on the server thread).
        self.score_drift = diag_lib.ServeScoreDrift(key="margin")

    # -- checkpoint (re)loading ------------------------------------------

    def _load_latest(self, required: bool = False):
        polled = self._watcher.poll()
        if polled is None and required and self.served_round < 0:
            # The watcher also reports None for TRANSIENT conditions (a
            # writer raced between its weight and tag renames, a file
            # rotating away mid-read).  At startup, only "nothing on
            # disk" is fatal; a present-but-racing checkpoint settles
            # within a publish, so retry briefly before giving up.
            path, _ = ckpt_lib.latest_best_ckpt(self.ckpt_dir)
            if path is None:
                raise FileNotFoundError(
                    f"no best_rd_*.msgpack under {self.ckpt_dir}")
            for _ in range(50):
                time.sleep(0.1)
                polled = self._watcher.poll()
                if polled is not None:
                    break
            else:
                raise RuntimeError(
                    f"best checkpoint under {self.ckpt_dir} never "
                    "settled (weights/tag publish kept racing)")
        if polled is None:
            return None
        variables, rd, tag = polled
        self.served_round = rd
        self.logger.info(
            f"serve: loaded best checkpoint of round {rd}"
            + (f" (best epoch {tag[1]})" if tag else ""))
        return variables

    def maybe_reload(self, now: Optional[float] = None) -> bool:
        """Between-batches hot reload: bounded-cadence poll for a newer
        best checkpoint; swap variables if one appeared.  Runs on the
        executor thread; safe to call from tests directly."""
        if self.ckpt_dir is None:
            return False
        now = time.monotonic() if now is None else now
        if now - self._last_reload_check < self.reload_every_s:
            return False
        self._last_reload_check = now
        prev_round = self.served_round
        variables = self._load_latest()
        if variables is None:
            return False
        self._variables = mesh_lib.replicate(variables, self.mesh)
        # What the OUTGOING checkpoint served becomes the drift
        # baseline; the new model's scores accumulate against it.
        self.score_drift.rebaseline(prev_round)
        with self._lock:
            self.stats["reloads"] += 1
        return True

    # -- warmup / compile accounting -------------------------------------

    def warmup(self, buckets: Sequence[int]) -> None:
        """Compile every (step, bucket) pair the request path can reach,
        then snapshot the jit-cache sizes as the zero-request-path-
        compiles baseline.  With the persistent compilation cache on,
        repeat server starts pay disk hits here, not compiles."""
        h, w, c = self.image_shape
        for b in sorted(set(int(x) for x in buckets)):
            host = {"image": np.zeros((b, h, w, c), dtype=np.uint8),
                    "mask": np.ones(b, dtype=np.float32)}
            if self.host_s2d:
                from ..data.pipeline import space_to_depth
                host = dict(host, image=space_to_depth(host["image"]))
            dev = mesh_lib.shard_batch(host, self.mesh)
            for step in self._steps.values():
                out = step(self._variables, dev)
                # Force completion so warmup compile time never leaks
                # into the first request's latency.
                for v in out.values():
                    np.asarray(v)
            with self._lock:
                self.stats["warm_buckets"].append(b)
        self._compile_baseline = self.compile_counts()

    def compile_counts(self) -> Dict[str, int]:
        """Live jit-cache entry counts per step — the compile counter of
        tests/test_compile_reuse.py, servable via /metrics."""
        return {name: int(step._cache_size())
                for name, step in self._steps.items()}

    def request_path_compiles(self) -> int:
        """Compiles since warmup(); 0 is the contract."""
        if self._compile_baseline is None:
            return -1
        counts = self.compile_counts()
        return sum(counts[k] - self._compile_baseline.get(k, 0)
                   for k in counts)

    # -- the device loop --------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run,
                                        name="al-serve-executor",
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 30.0) -> None:
        """Process everything queued, then stop the thread.  FIFO: the
        shutdown sentinel queues behind in-flight batches, so stop()
        after batcher.drain() completes every admitted request."""
        if self._thread is None:
            return
        self._inq.put(_SHUTDOWN)
        self._thread.join(timeout=timeout)
        self._thread = None

    def submit_batch(self, host_batch: Dict[str, np.ndarray],
                     entries: List, want_embed: bool) -> None:
        """Batcher dispatch target (thread-safe, non-blocking)."""
        self._inq.put((host_batch, entries, want_embed))

    def _put(self, item):
        """Feeder-thread H2D shard.  MUST NOT raise: device_prefetch
        re-raises feeder exceptions at the consuming ``for``, OUTSIDE
        the per-batch try below — one transient device_put failure
        (e.g. HBM pressure beside a live training run) would kill the
        executor thread and leave every queued future hanging.  Errors
        ride along as a marker instead and fail only their own batch."""
        host_batch, entries, want_embed = item
        try:
            if self.host_s2d:
                from ..data.pipeline import space_to_depth
                host_batch = dict(host_batch,
                                  image=space_to_depth(host_batch["image"]))
            dev = mesh_lib.shard_batch(host_batch, self.mesh)
            return (dev, entries, want_embed, None)
        except Exception as exc:  # noqa: BLE001 - per-batch isolation
            return (None, entries, want_embed, exc)

    def _run(self) -> None:
        from ..data.cache import device_prefetch

        def host_items():
            while True:
                item = self._inq.get()
                if item is _SHUTDOWN:
                    return
                yield item

        # The h2d dispatch of batch n+1 overlaps batch n's compute —
        # the same double-buffering as the offline streaming fallback.
        for dev_batch, entries, want_embed, put_exc in device_prefetch(
                host_items(), self._put, depth=self.prefetch_depth):
            if put_exc is not None:
                self.logger.error(f"serve: h2d shard failed: {put_exc!r}")
                for e in entries:
                    _reject(e.future, put_exc)
                continue
            try:
                self.maybe_reload()
                out = self._steps["prob_stats"](self._variables, dev_batch)
                host = {k: np.asarray(out[k]) for k in STAT_KEYS}
                if want_embed:
                    emb = self._steps["embed"](self._variables, dev_batch)
                    host["embedding"] = np.asarray(emb["embedding"])
                with self._lock:
                    self.stats["batches"] += 1
                    self.stats["rows"] += sum(e.n for e in entries)
                for e in entries:
                    sl = slice(e.offset, e.offset + e.n)
                    # Real rows only (the bucket's padding tail would
                    # poison the distribution); the margin array is
                    # already on host for the response.
                    self.score_drift.observe(host["margin"][sl])
                    payload = {k: v[sl] for k, v in host.items()
                               if k != "embedding" or e.want_embed}
                    payload["round"] = self.served_round
                    _resolve(e.future, payload)
            except Exception as exc:  # noqa: BLE001 - per-batch isolation
                self.logger.exception("serve: batch failed")
                for e in entries:
                    _reject(e.future, exc)


def _resolve(future, payload) -> None:
    loop = future.get_loop()
    loop.call_soon_threadsafe(
        lambda: future.set_result(payload) if not future.done() else None)


def _reject(future, exc: Exception) -> None:
    loop = future.get_loop()
    loop.call_soon_threadsafe(
        lambda: future.set_exception(exc) if not future.done() else None)
