"""The ``serve`` CLI verb.

    python -m active_learning_tpu serve --experiment_dir ./checkpoint/myexp_abc123
    # or, addressing the experiment the way the training CLI does:
    python -m active_learning_tpu serve --ckpt_path ./checkpoint \\
        --exp_name myexp --exp_hash abc123

Everything about the served model is resolved from the experiment
itself: the saved config echo (experiment_state.json, written every
round by experiment/resume.py) names the dataset and model, the newest
``best_rd_{n}.msgpack`` provides the weights, and the checkpoint's own
classifier-head shape provides num_classes — so a finished OR still-
running experiment serves with one flag.  While the experiment keeps
training, the executor hot-reloads each new round's best checkpoint
between batches.

The persistent XLA compilation cache is enabled exactly as the driver
does it, so the startup bucket warmup is disk hits after the first
server start on a machine — and because the bucket ladder and the
offline scoring steps are shared with the driver, a server started on a
machine that already ran the experiment warms from the experiment's own
cache entries.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
from typing import List, Optional, Tuple

from ..config import ServeConfig

# Dataset name -> (val ViewSpec factory, default image size).  The val
# view is THE scoring view (al_set.view in the offline path); serving
# with any other transform would break served==offline score equality.
_DATASET_VIEWS = {
    "cifar10": ("cifar", 32),
    "imbalanced_cifar10": ("cifar", 32),
    "imagenet": ("imagenet", 224),
    "imbalanced_imagenet": ("imagenet", 224),
    "synthetic": ("synthetic", 32),
}


def get_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m active_learning_tpu serve",
        description="Serve predictions + acquisition scores from an AL "
                    "experiment's best checkpoint")
    p.add_argument("--experiment_dir", type=str, default=None,
                   help="the experiment's checkpoint directory "
                        "({ckpt_path}/{exp_name}_{exp_hash}); holds "
                        "best_rd_*.msgpack + experiment_state.json")
    p.add_argument("--ckpt_path", type=str, default="./checkpoint")
    p.add_argument("--exp_name", type=str, default=None)
    p.add_argument("--exp_hash", type=str, default=None)
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 = ephemeral (logged at startup)")
    p.add_argument("--max_batch", type=int, default=64)
    p.add_argument("--max_latency_ms", type=float, default=5.0)
    p.add_argument("--queue_depth", type=int, default=512)
    p.add_argument("--bucket_floor", type=int, default=8)
    p.add_argument("--reload_every_s", type=float, default=5.0)
    p.add_argument("--drain_timeout_s", type=float, default=30.0)
    p.add_argument("--dataset", type=str, default=None,
                   help="override the experiment's saved dataset name")
    p.add_argument("--model", type=str, default=None,
                   help="override the experiment's saved model name")
    p.add_argument("--image_size", type=int, default=None,
                   help="served input H=W (default: by dataset)")
    p.add_argument("--num_devices", type=int, default=-1)
    p.add_argument("--compilation_cache_dir", type=str, default=None)
    p.add_argument("--log_dir", type=str, default="./logs",
                   help="serve log file directory (console always on)")
    return p


def resolve_experiment_dir(args) -> str:
    if args.experiment_dir:
        return args.experiment_dir
    if args.exp_name and args.exp_hash:
        return os.path.join(args.ckpt_path,
                            f"{args.exp_name}_{args.exp_hash}")
    raise SystemExit("serve: pass --experiment_dir, or --exp_name + "
                     "--exp_hash (+ --ckpt_path)")


def load_experiment_meta(exp_dir: str) -> dict:
    """The flattened config echo of the experiment's last saved round
    (experiment/resume.py META_FILE); {} when the experiment has not
    saved a round yet (weights alone still serve)."""
    import json

    path = os.path.join(exp_dir, "experiment_state.json")
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        return json.load(fh).get("config", {})


def resolve_serve_setup(args) -> Tuple[object, dict, object, int, str]:
    """(model, variables, view, image_size, exp_dir) from the CLI args +
    the experiment's own artifacts.  num_classes comes from the
    checkpoint's classifier-head bias — the one place it cannot lie —
    and the stem/dtype/BN-stats choices follow the driver's exact
    resolution (config echo beats arg pool, experiment/driver.py):
    an experiment trained with --stem s2d saved a FOLDED 4x4x12 stem
    kernel, and serving it with the default model would die on the
    param-shape mismatch at warmup."""
    from ..data.core import CIFAR10_NORM, IMAGENET_NORM, ViewSpec
    from ..data.synthetic import SYNTH_NORM
    from ..experiment.arg_pools import get_train_config
    from ..models.factory import get_network
    from ..train import checkpoint as ckpt_lib

    exp_dir = resolve_experiment_dir(args)
    cfg_echo = load_experiment_meta(exp_dir)
    dataset = args.dataset or cfg_echo.get("dataset") or "cifar10"
    model_name = args.model or cfg_echo.get("model") or "SSLResNet18"
    best_path, rd = ckpt_lib.latest_best_ckpt(exp_dir)
    if best_path is None:
        raise SystemExit(f"serve: no best_rd_*.msgpack under {exp_dir}")
    variables = ckpt_lib.load_variables(best_path)
    num_classes = int(variables["params"]["linear"]["bias"].shape[0])

    # The driver's model-config resolution, replayed: explicit CLI echo
    # beats the arg pool's TrainConfig (driver.py build_experiment).
    try:
        train_cfg = get_train_config(cfg_echo.get("arg_pool", "default"),
                                     dataset)
    except KeyError:
        train_cfg = None
    def resolved(key, default):
        return (cfg_echo.get(key)
                or (getattr(train_cfg, key) if train_cfg else None)
                or default)
    stem = resolved("stem", "default")
    dtype = resolved("dtype", "auto")
    bn_stats = resolved("bn_stats_dtype", "auto")

    view_kind, default_size = _DATASET_VIEWS.get(dataset, ("cifar", 32))
    norm = {"cifar": CIFAR10_NORM, "imagenet": IMAGENET_NORM,
            "synthetic": SYNTH_NORM}[view_kind]
    view = ViewSpec(norm, augment=False)
    image_size = int(args.image_size or default_size)
    model = get_network(dataset, model_name, num_classes=num_classes,
                        dtype=dtype, stem=stem, bn_stats_dtype=bn_stats)
    return model, variables, view, image_size, exp_dir


def main(argv: Optional[List[str]] = None) -> int:
    args = get_parser().parse_args(argv)
    serve_cfg = ServeConfig(
        host=args.host, port=args.port, max_batch=args.max_batch,
        max_latency_ms=args.max_latency_ms, queue_depth=args.queue_depth,
        bucket_floor=args.bucket_floor, reload_every_s=args.reload_every_s,
        drain_timeout_s=args.drain_timeout_s)

    # Without a handler the "listening on"/"drained cleanly" lines — the
    # operator's only confirmation — would vanish into a handlerless
    # logger; same setup as the driver, file + console.
    import datetime as dt

    from ..utils.logging import setup_logging
    setup_logging(args.log_dir,
                  f"serve_{dt.date.today():%m%d}_{os.getpid()}.log")

    # Same persistent-cache discipline as the training driver: the
    # bucket warmup below becomes disk hits on the second server start.
    from ..experiment.driver import enable_compilation_cache
    enable_compilation_cache(args.compilation_cache_dir)

    model, variables, view, image_size, exp_dir = resolve_serve_setup(args)

    from ..parallel import mesh as mesh_lib
    from .executor import DeviceExecutor
    from .server import ScoringServer

    mesh = mesh_lib.make_mesh(args.num_devices)
    # variables from resolve_serve_setup were only for num_classes
    # inference; the executor loads the checkpoint itself so its
    # (round, mtime) stamp — and the round stamped on every response —
    # describe the file actually served.
    del variables
    executor = DeviceExecutor(
        model, view, mesh, image_shape=(image_size, image_size, 3),
        ckpt_dir=exp_dir, reload_every_s=serve_cfg.reload_every_s,
        # Same gate the offline scoring path uses (strategies/base.py
        # _resident_kwargs): clients send (H, W, 3) rows; the s2d
        # re-layout the folded stem expects happens host-side here.
        host_s2d=getattr(model, "stem", "default") == "s2d")
    server = ScoringServer(executor, serve_cfg)
    asyncio.run(_serve_until_signal(server))
    return 0


async def _serve_until_signal(server) -> None:
    """Run until SIGTERM/SIGINT, then drain: stop accepting, complete
    every admitted request, stop the device loop, return (exit 0)."""
    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await server.start()
    await stop.wait()
    await server.drain()


if __name__ == "__main__":
    raise SystemExit(main())
