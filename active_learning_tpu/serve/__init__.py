"""Scoring-as-a-service: an async batched TPU inference subsystem.

Every capability in this framework — sharded scoring, resident pools,
bucketed compiled shapes, the persistent compilation cache — was until
now only reachable through the offline AL driver.  This package opens
the ONLINE path: a labeling frontend (or any HTTP client) streams
images and gets back predictions AND acquisition scores
(margin/entropy/embedding) from the best checkpoint of a live or
finished AL experiment.

Architecture (the Podracer decoupling, arXiv:2104.06272: a continuously
running device executor fed by asynchronous request producers keeps the
accelerator saturated under irregular load):

  * ``batcher``  — an asyncio microbatching queue: requests coalesce up
    to ``max_batch`` rows or a ``max_latency_ms`` deadline, whichever
    comes first, and every dispatched batch is padded to a geometric
    bucket (pool.bucket_size) so the served shape set is small, fixed,
    and pre-compiled.  Bounded admission (429 upstream) and carry-over
    so a batch never exceeds ``max_batch``.
  * ``executor`` — ONE device-executor loop over the persistent mesh:
    loads ``best_rd_{n}`` via the existing checkpoint machinery, runs
    the SAME jitted scoring steps the offline path uses
    (strategies/scoring.make_prob_stats_step / make_embed_step — served
    outputs are bit-for-bit the offline scores at the same batch
    shape), double-buffers host->device transfer through
    data/cache.device_prefetch, and hot-reloads a newer round's best
    checkpoint between batches so a running experiment is served
    without downtime.
  * ``server``   — stdlib-asyncio HTTP front end: POST /v1/predict,
    POST /v1/score, GET /healthz, GET /metrics; explicit backpressure
    (429 + Retry-After when the queue is full) and graceful drain on
    SIGTERM (in-flight requests complete, then the process exits 0).
  * ``cli``      — the ``serve`` verb (``python -m active_learning_tpu
    serve --experiment_dir ...``), resolving model/dataset/view from
    the experiment's saved config echo and the checkpoint's own head
    shape.

No dependencies beyond the stdlib and the existing JAX stack.  Request
latency — not round wall-clock — is this subsystem's metric; see
``scripts/serve_loadgen.py`` and the ``serve_throughput`` bench phase.
"""

from .batcher import MicroBatcher, QueueFullError, serve_buckets  # noqa: F401
from .executor import DeviceExecutor  # noqa: F401
from .server import ScoringServer  # noqa: F401
