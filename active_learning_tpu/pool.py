"""Active-learning pool bookkeeping.

Replaces the mask-bookkeeping spread across the reference's ``Strategy`` base
class (``idxs_lb``/``idxs_lb_recent``/``eval_idxs``/``cumulative_cost`` and
the methods ``available_query_idxs``/``already_labeled_idxs``/``update``,
src/query_strategies/strategy.py:97-163,459-485) with an explicit, picklable
dataclass.  All randomness is taken from an injected ``numpy`` Generator so
runs are reproducible end-to-end (the reference relies on the global
``np.random`` state).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np


def bucket_size(n: int, floor: int = 256) -> int:
    """Bounded-waste geometric bucket: ``n`` rounded up to a multiple of
    1/8 of its enclosing power of two (never below ``floor``).

    THE shape-bucketing rule for everything whose size tracks the growing
    labeled set (or the shrinking unlabeled pool) across AL rounds: the
    trainer's epoch-scan step count, its device-resident row upload, and
    the k-center selection pool are all padded to this bucket so round
    N+1 reuses round N's compiled executables instead of paying a fresh
    XLA compile per round (padding is masked out of every computation by
    the callers).

    Why not plain next-power-of-two: the padding is masked out of the
    RESULTS but not the COMPUTE — a padded epoch-scan step still runs a
    full train step, a padded pool row still rides every distance matmul
    — so just past a pow2 boundary pure pow2 buckets would re-spend up
    to ~2x compute on EVERY epoch/pick to save one recompile per round.
    The 1/8-octave granularity caps that recurring waste at 25%
    worst-case (just past a power of two; typically well under 10%)
    while keeping the distinct-shape count small (8 buckets per
    doubling) so consecutive rounds still reuse executables.  ``floor``
    pins tiny inputs to one fixed bucket.
    """
    n = max(int(n), int(floor))
    gran = max(int(floor), (1 << (n - 1).bit_length()) // 8)
    return -(-n // gran) * gran


@dataclasses.dataclass
class PoolState:
    """Boolean-mask view of the unlabeled pool.

    Attributes:
      n_pool: total number of candidate examples (== len(al_set)).
      labeled: bool[n_pool]; True where the example has been labeled.
      recent: indices labeled by the most recent ``update`` call.
      eval_idxs: validation indices carved out of the train set; never
        queryable (strategy.py:138,144).
      cumulative_cost: total budget spent so far.
      round: current AL round.
    """

    n_pool: int
    labeled: np.ndarray
    eval_idxs: np.ndarray
    recent: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, dtype=np.int64))
    cumulative_cost: float = 0.0
    round: int = 0

    @classmethod
    def create(cls, n_pool: int, eval_idxs: Sequence[int]) -> "PoolState":
        return cls(
            n_pool=int(n_pool),
            labeled=np.zeros(n_pool, dtype=bool),
            eval_idxs=np.asarray(eval_idxs, dtype=np.int64),
        )

    # -- queries ---------------------------------------------------------

    def available_mask(self) -> np.ndarray:
        """Bool mask of queryable examples: unlabeled and not in the eval
        split (strategy.py:139-142)."""
        mask = ~self.labeled
        if self.eval_idxs.size:
            mask[self.eval_idxs] = False
        return mask

    def available_query_idxs(
        self,
        shuffle: bool = True,
        rng: Optional[np.random.Generator] = None,
    ) -> np.ndarray:
        """Indices of queryable examples, optionally shuffled
        (strategy.py:143-145: shuffle precedes eval-idx filtering, so the
        order is a permutation of the unlabeled set)."""
        idxs = np.flatnonzero(self.available_mask())
        if shuffle:
            if rng is None:
                raise ValueError("shuffle=True requires an explicit rng")
            idxs = rng.permutation(idxs)
        return idxs

    def labeled_idxs(
        self,
        shuffle: bool = False,
        rng: Optional[np.random.Generator] = None,
    ) -> np.ndarray:
        idxs = np.flatnonzero(self.labeled)
        if shuffle:
            if rng is None:
                raise ValueError("shuffle=True requires an explicit rng")
            idxs = rng.permutation(idxs)
        return idxs

    def labeled_mask(self) -> np.ndarray:
        return self.labeled.copy()

    @property
    def num_labeled(self) -> int:
        return int(self.labeled.sum())

    @property
    def num_available(self) -> int:
        return int(self.available_mask().sum())

    # -- mutation --------------------------------------------------------

    def update(self, labeled_idxs: Sequence[int], cost: float) -> None:
        """Mark ``labeled_idxs`` as labeled; add ``cost`` to the budget.

        Enforces the reference's invariants (strategy.py:468-471): no
        example may be labeled twice, and a query batch may not contain
        duplicates.
        """
        idxs = np.asarray(labeled_idxs, dtype=np.int64).reshape(-1)
        if idxs.size:
            if idxs.min() < 0 or idxs.max() >= self.n_pool:
                raise ValueError(
                    f"indices out of range [0, {self.n_pool}): "
                    f"{idxs[(idxs < 0) | (idxs >= self.n_pool)][:10].tolist()}")
            if np.unique(idxs).size != idxs.size:
                raise ValueError("query returned duplicate indices")
            if self.labeled[idxs].any():
                dup = idxs[self.labeled[idxs]][:10]
                raise ValueError(
                    f"examples already labeled: {dup.tolist()}")
            if self.eval_idxs.size and np.isin(idxs, self.eval_idxs).any():
                raise ValueError("query returned validation indices")
            self.labeled[idxs] = True
        self.recent = idxs
        self.cumulative_cost += float(cost)

    # -- (de)serialization ----------------------------------------------

    def to_arrays(self) -> dict:
        return {
            "n_pool": np.asarray(self.n_pool),
            "labeled": self.labeled.copy(),
            "eval_idxs": self.eval_idxs.copy(),
            "recent": self.recent.copy(),
            "cumulative_cost": np.asarray(self.cumulative_cost),
            "round": np.asarray(self.round),
        }

    @classmethod
    def from_arrays(cls, arrs: dict) -> "PoolState":
        return cls(
            n_pool=int(arrs["n_pool"]),
            labeled=np.array(arrs["labeled"], dtype=bool, copy=True),
            eval_idxs=np.array(arrs["eval_idxs"], dtype=np.int64, copy=True),
            recent=np.array(arrs["recent"], dtype=np.int64, copy=True),
            cumulative_cost=float(arrs["cumulative_cost"]),
            round=int(arrs["round"]),
        )
