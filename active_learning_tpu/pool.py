"""Active-learning pool bookkeeping.

Replaces the mask-bookkeeping spread across the reference's ``Strategy`` base
class (``idxs_lb``/``idxs_lb_recent``/``eval_idxs``/``cumulative_cost`` and
the methods ``available_query_idxs``/``already_labeled_idxs``/``update``,
src/query_strategies/strategy.py:97-163,459-485) with an explicit, picklable
dataclass.  All randomness is taken from an injected ``numpy`` Generator so
runs are reproducible end-to-end (the reference relies on the global
``np.random`` state).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np


def bucket_size(n: int, floor: int = 256) -> int:
    """Bounded-waste geometric bucket: ``n`` rounded up to a multiple of
    1/8 of its enclosing power of two (never below ``floor``).

    THE shape-bucketing rule for everything whose size tracks the growing
    labeled set (or the shrinking unlabeled pool) across AL rounds: the
    trainer's epoch-scan step count, its device-resident row upload, and
    the k-center selection pool are all padded to this bucket so round
    N+1 reuses round N's compiled executables instead of paying a fresh
    XLA compile per round (padding is masked out of every computation by
    the callers).

    Why not plain next-power-of-two: the padding is masked out of the
    RESULTS but not the COMPUTE — a padded epoch-scan step still runs a
    full train step, a padded pool row still rides every distance matmul
    — so just past a pow2 boundary pure pow2 buckets would re-spend up
    to ~2x compute on EVERY epoch/pick to save one recompile per round.
    The 1/8-octave granularity caps that recurring waste at 25%
    worst-case (just past a power of two; typically well under 10%)
    while keeping the distinct-shape count small (8 buckets per
    doubling) so consecutive rounds still reuse executables.  ``floor``
    pins tiny inputs to one fixed bucket.
    """
    n = max(int(n), int(floor))
    gran = max(int(floor), (1 << (n - 1).bit_length()) // 8)
    return -(-n // gran) * gran


@dataclasses.dataclass
class PoolState:
    """Boolean-mask view of the unlabeled pool.

    Attributes:
      n_pool: total number of candidate examples (== len(al_set)).
      labeled: bool[n_pool]; True where the example has been labeled.
      recent: indices labeled by the most recent ``update`` call.
      eval_idxs: validation indices carved out of the train set; never
        queryable (strategy.py:138,144).
      invalid: bool[n_pool]; True for slots that hold NO real example —
        the streaming subsystem (active_learning_tpu/stream/) grows the
        pool by bucket_size-aligned extents so the resident-upload shape
        ladder stays enumerable, and the padding slots between the valid
        row count and the extent capacity are neither queryable, nor
        labelable, nor eval.  A frozen-disk-pool experiment (the
        reference protocol) never sets any of these.
      cumulative_cost: total budget spent so far.
      round: current AL round.
    """

    n_pool: int
    labeled: np.ndarray
    eval_idxs: np.ndarray
    recent: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, dtype=np.int64))
    cumulative_cost: float = 0.0
    round: int = 0
    invalid: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros(0, dtype=bool))

    def __post_init__(self):
        if self.invalid.size == 0 and self.n_pool:
            self.invalid = np.zeros(self.n_pool, dtype=bool)

    @classmethod
    def create(cls, n_pool: int, eval_idxs: Sequence[int]) -> "PoolState":
        return cls(
            n_pool=int(n_pool),
            labeled=np.zeros(n_pool, dtype=bool),
            eval_idxs=np.asarray(eval_idxs, dtype=np.int64),
        )

    # -- queries ---------------------------------------------------------

    def available_mask(self) -> np.ndarray:
        """Bool mask of queryable examples: unlabeled, not in the eval
        split (strategy.py:139-142), and not a padding/placeholder slot
        (``invalid``)."""
        mask = ~self.labeled
        if self.eval_idxs.size:
            mask[self.eval_idxs] = False
        if self.invalid.size:
            mask &= ~self.invalid
        return mask

    def available_query_idxs(
        self,
        shuffle: bool = True,
        rng: Optional[np.random.Generator] = None,
    ) -> np.ndarray:
        """Indices of queryable examples, optionally shuffled
        (strategy.py:143-145: shuffle precedes eval-idx filtering, so the
        order is a permutation of the unlabeled set)."""
        idxs = np.flatnonzero(self.available_mask())
        if shuffle:
            if rng is None:
                raise ValueError("shuffle=True requires an explicit rng")
            idxs = rng.permutation(idxs)
        return idxs

    def labeled_idxs(
        self,
        shuffle: bool = False,
        rng: Optional[np.random.Generator] = None,
    ) -> np.ndarray:
        idxs = np.flatnonzero(self.labeled)
        if shuffle:
            if rng is None:
                raise ValueError("shuffle=True requires an explicit rng")
            idxs = rng.permutation(idxs)
        return idxs

    def labeled_mask(self) -> np.ndarray:
        return self.labeled.copy()

    @property
    def num_labeled(self) -> int:
        return int(self.labeled.sum())

    @property
    def num_available(self) -> int:
        return int(self.available_mask().sum())

    # -- mutation --------------------------------------------------------

    def update(self, labeled_idxs: Sequence[int], cost: float) -> None:
        """Mark ``labeled_idxs`` as labeled; add ``cost`` to the budget.

        Enforces the reference's invariants (strategy.py:468-471): no
        example may be labeled twice, and a query batch may not contain
        duplicates.
        """
        idxs = np.asarray(labeled_idxs, dtype=np.int64).reshape(-1)
        if idxs.size:
            if idxs.min() < 0 or idxs.max() >= self.n_pool:
                raise ValueError(
                    f"indices out of range [0, {self.n_pool}): "
                    f"{idxs[(idxs < 0) | (idxs >= self.n_pool)][:10].tolist()}")
            if np.unique(idxs).size != idxs.size:
                raise ValueError("query returned duplicate indices")
            if self.labeled[idxs].any():
                dup = idxs[self.labeled[idxs]][:10]
                raise ValueError(
                    f"examples already labeled: {dup.tolist()}")
            if self.eval_idxs.size and np.isin(idxs, self.eval_idxs).any():
                raise ValueError("query returned validation indices")
            if self.invalid.size and self.invalid[idxs].any():
                bad = idxs[self.invalid[idxs]][:10]
                raise ValueError(
                    f"query returned invalid (padding) slots: {bad.tolist()}")
            self.labeled[idxs] = True
        self.recent = idxs
        self.cumulative_cost += float(cost)

    # -- streaming growth (active_learning_tpu/stream/) -------------------

    def grow(self, n_pool: int) -> None:
        """Extend the pool to ``n_pool`` slots.  New slots arrive INVALID
        (padding) — ``set_valid`` opens them once real rows land in them.
        Shrinking is refused: pool slots are append-only so index i means
        the same example for the life of the experiment (the WAL/resume
        contract of the streaming subsystem depends on it)."""
        n_pool = int(n_pool)
        if n_pool < self.n_pool:
            raise ValueError(
                f"pool cannot shrink ({self.n_pool} -> {n_pool}); slots "
                "are append-only")
        if n_pool == self.n_pool:
            return
        extra = n_pool - self.n_pool
        self.labeled = np.concatenate(
            [self.labeled, np.zeros(extra, dtype=bool)])
        self.invalid = np.concatenate(
            [self.invalid if self.invalid.size else
             np.zeros(self.n_pool, dtype=bool),
             np.ones(extra, dtype=bool)])
        self.n_pool = n_pool

    def set_valid(self, n_valid: int) -> None:
        """Rows [0, n_valid) hold real examples; [n_valid, n_pool) stay
        padding.  Monotone: a slot once valid never goes back."""
        n_valid = int(n_valid)
        if n_valid > self.n_pool:
            raise ValueError(f"n_valid {n_valid} exceeds pool {self.n_pool}")
        if self.invalid.size == 0:
            self.invalid = np.zeros(self.n_pool, dtype=bool)
        self.invalid[:n_valid] = False

    def mark_valid(self, idxs: Sequence[int]) -> None:
        """Open specific slots: real (oracle-labeled) rows just landed
        in them — the streaming drain's per-extent validation."""
        idxs = np.asarray(idxs, dtype=np.int64).reshape(-1)
        if idxs.size:
            self.invalid[idxs] = False

    def mark_invalid(self, idxs: Sequence[int]) -> None:
        """Mark specific slots as placeholders (e.g. ingested rows with
        no oracle label yet — scoreable later, but not queryable)."""
        idxs = np.asarray(idxs, dtype=np.int64).reshape(-1)
        if idxs.size:
            if self.labeled[idxs].any():
                raise ValueError("cannot invalidate labeled slots")
            self.invalid[idxs] = True

    def absorb_labels(self, idxs: Sequence[int]) -> None:
        """Mark externally-labeled rows (the streaming /v1/label path) as
        labeled WITHOUT consuming budget or touching ``recent`` — these
        rows were never queried; their labels arrived from outside the
        loop.  Slots become valid as a side effect (a label IS the
        missing oracle information)."""
        idxs = np.asarray(idxs, dtype=np.int64).reshape(-1)
        if idxs.size == 0:
            return
        if idxs.min() < 0 or idxs.max() >= self.n_pool:
            raise ValueError(f"label indices out of range [0, {self.n_pool})")
        if self.labeled[idxs].any():
            dup = idxs[self.labeled[idxs]][:10]
            raise ValueError(f"rows already labeled: {dup.tolist()}")
        if self.eval_idxs.size and np.isin(idxs, self.eval_idxs).any():
            raise ValueError("cannot attach labels to validation rows")
        self.invalid[idxs] = False
        self.labeled[idxs] = True

    # -- (de)serialization ----------------------------------------------

    def to_arrays(self) -> dict:
        return {
            "n_pool": np.asarray(self.n_pool),
            "labeled": self.labeled.copy(),
            "eval_idxs": self.eval_idxs.copy(),
            "recent": self.recent.copy(),
            "cumulative_cost": np.asarray(self.cumulative_cost),
            "round": np.asarray(self.round),
            "invalid": (self.invalid.copy() if self.invalid.size else
                        np.zeros(self.n_pool, dtype=bool)),
        }

    @classmethod
    def from_arrays(cls, arrs: dict) -> "PoolState":
        n_pool = int(arrs["n_pool"])
        # Pre-stream saves carry no invalid mask: all slots are real.
        invalid = (np.array(arrs["invalid"], dtype=bool, copy=True)
                   if "invalid" in arrs else np.zeros(n_pool, dtype=bool))
        return cls(
            n_pool=n_pool,
            labeled=np.array(arrs["labeled"], dtype=bool, copy=True),
            eval_idxs=np.array(arrs["eval_idxs"], dtype=np.int64, copy=True),
            recent=np.array(arrs["recent"], dtype=np.int64, copy=True),
            cumulative_cost=float(arrs["cumulative_cost"]),
            round=int(arrs["round"]),
            invalid=invalid,
        )
